"""Continuous-batching serving: slot-pool engine parity, bounded
compilation, scheduler policies, transports, and telemetry.

Everything runs under JAX_PLATFORMS=cpu with a tiny model — the full
engine (prefill buckets, ragged batched decode, runtime per-slot sampling,
backpressure, HTTP) is tier-1-testable without a chip.
"""

import dataclasses
import json
import os
import pickle
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bpe_transformer_tpu.models import TS_TEST_CONFIG, init_params
from bpe_transformer_tpu.serving import (
    FifoScheduler,
    QueueFullError,
    Request,
    ServingEngine,
    SlotPoolEngine,
    default_prefill_buckets,
    make_http_server,
)
from bpe_transformer_tpu.serving.engine import sample_tokens
from bpe_transformer_tpu.telemetry import Telemetry
from bpe_transformer_tpu.telemetry.report import render_report, summarize

pytestmark = pytest.mark.serving

REPO = Path(__file__).resolve().parent.parent

CFG = dataclasses.replace(TS_TEST_CONFIG, vocab_size=128, context_length=32)


@pytest.fixture(scope="module")
def setup():
    # Prompt lengths span three buckets (8, 16, 32); the parity oracle
    # (generate_ids) compiles one scan program per (length, budget) shape,
    # so tests below reuse these exact shapes to share the jit cache —
    # tier-1 wall time is mostly those reference-side compiles.
    params = init_params(jax.random.PRNGKey(0), CFG)
    rng = np.random.default_rng(0)
    prompts = [
        [int(t) for t in rng.integers(0, CFG.vocab_size, size=n)]
        for n in (3, 7, 12, 19)
    ]
    return params, prompts


# ------------------------------------------------------------------ engine


@pytest.mark.slow
def test_batched_parity_with_sequential_sampling(setup):
    """ACCEPTANCE: at temperature=0 the engine serving ragged prompts
    through a 3-slot pool produces byte-identical completions to sequential
    per-prompt `sampling.generate_ids` calls — continuous batching changes
    throughput, never tokens."""
    from bpe_transformer_tpu.training.sampling import generate_ids

    params, prompts = setup
    with ServingEngine(params, CFG, slots=3, min_bucket=8) as serving:
        results = serving.run_batch(prompts, max_new_tokens=8, temperature=0.0)
    for prompt, result in zip(prompts, results):
        expected = generate_ids(
            params, CFG, prompt, max_new_tokens=8, temperature=0.0
        )
        assert list(result.token_ids) == expected
        assert result.finish_reason == "length"


def test_bounded_compilation_over_mixed_lengths(setup):
    """ACCEPTANCE: after warmup over mixed prompt lengths AND mixed sampling
    knobs, the engine has compiled at most len(buckets) + 1 programs —
    sampling knobs are runtime values, prompt shapes come from the bucket
    set, so requests never recompile."""
    params, prompts = setup
    engine = SlotPoolEngine(params, CFG, slots=2, min_bucket=8)
    assert engine.buckets == (8, 16, 32)

    knobs = [
        dict(temperature=0.0),
        dict(temperature=0.7, top_k=5),
        dict(temperature=1.3, top_p=0.9),
        dict(temperature=0.9, top_k=7, top_p=0.8, seed=3),
        dict(temperature=0.5),
        dict(temperature=1.0, top_k=2),
    ]
    for prompt, kn in zip(prompts, knobs):
        event = engine.admit(prompt, max_new_tokens=4, **kn)
        while not event.finished:
            events = engine.tick()
            event = next(e for e in events if e.slot == event.slot)
    assert engine.compiled_programs() <= len(engine.buckets) + 1


@pytest.mark.slow  # 870s tier-1 budget (PR 11 sweep; ISSUE 11 tooling guard) — runs in the full matrix
def test_slot_reuse_and_interleaved_admission(setup):
    """More requests than slots: retired slots are re-admitted mid-flight
    and each request still matches its solo greedy generation."""
    from bpe_transformer_tpu.training.sampling import generate_ids

    params, prompts = setup
    engine = SlotPoolEngine(params, CFG, slots=2, min_bucket=8)
    # Ragged budgets stagger retirements (mid-flight re-admission); two of
    # the (length, budget) oracle shapes are shared with the parity test.
    budgets = [8, 3, 8, 5]
    outputs = {i: [] for i in range(len(prompts))}
    pending = list(range(len(prompts)))
    slot_req: dict[int, int] = {}

    while pending or slot_req:
        while pending and engine.free_slots:
            idx = pending.pop(0)
            event = engine.admit(
                prompts[idx], max_new_tokens=budgets[idx], temperature=0.0
            )
            outputs[idx].append(event.token)
            if not event.finished:
                slot_req[event.slot] = idx
        for event in engine.tick():
            idx = slot_req.get(event.slot)
            if idx is None:
                continue
            outputs[idx].append(event.token)
            if event.finished:
                del slot_req[event.slot]

    for idx, prompt in enumerate(prompts):
        expected = generate_ids(
            params, CFG, prompt, max_new_tokens=budgets[idx], temperature=0.0
        )
        assert outputs[idx] == expected, f"request {idx}"


def test_engine_stop_id_retires_slot(setup):
    """A slot retires with reason "stop" at the stop id, matching the
    sequential sampler's truncation."""
    from bpe_transformer_tpu.training.sampling import generate_ids

    params, prompts = setup
    free_run = generate_ids(
        params, CFG, prompts[0], max_new_tokens=8, temperature=0.0
    )
    sid = free_run[3]
    expected = generate_ids(
        params, CFG, prompts[0], max_new_tokens=8, temperature=0.0,
        stop_id=sid,
    )
    with ServingEngine(params, CFG, slots=1, min_bucket=8) as serving:
        result = serving.generate(
            prompts[0], max_new_tokens=8, temperature=0.0, stop_id=sid
        )
    assert result.finish_reason == "stop"
    assert list(result.token_ids) == expected
    assert result.token_ids[-1] == sid
    assert sid not in result.token_ids[:-1]


def test_prompt_validation_and_bucket_policy(setup):
    params, _ = setup
    engine = SlotPoolEngine(params, CFG, slots=1, min_bucket=8)
    assert engine.bucket_for(1) == 8
    assert engine.bucket_for(8) == 8
    assert engine.bucket_for(9) == 16
    assert engine.bucket_for(32) == 32
    with pytest.raises(ValueError, match="exceeds"):
        engine.bucket_for(33)
    with pytest.raises(ValueError, match="no room"):
        engine.admit([1] * 32, max_new_tokens=4)
    with pytest.raises(ValueError, match="at least one token"):
        engine.admit([], max_new_tokens=4)
    assert default_prefill_buckets(100, 16) == (16, 32, 64, 100)


def test_max_new_tokens_clamped_to_context(setup):
    """A budget larger than the remaining context finishes with "length"
    exactly when the window fills — never an out-of-range cache write."""
    params, _ = setup
    with ServingEngine(params, CFG, slots=1, min_bucket=8) as serving:
        result = serving.generate(
            [1, 2, 3], max_new_tokens=1000, temperature=0.0
        )
    assert result.finish_reason == "length"
    assert len(result.token_ids) == CFG.context_length - 3


# ----------------------------------------------------------------- sampler


def test_runtime_sampler_per_row_knobs():
    """One batch, per-row knobs: greedy, top_k=1, tight nucleus, and free
    sampling coexist in one call without affecting each other."""
    logits = jnp.log(
        jnp.tile(jnp.asarray([[0.6, 0.25, 0.1, 0.04, 0.01]]), (4, 1))
    )
    seen: dict[int, set] = {0: set(), 1: set(), 2: set(), 3: set()}
    for seed in range(24):
        keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(4) + 97 * seed)
        toks = sample_tokens(
            logits,
            keys,
            temps=jnp.asarray([0.0, 1.0, 1.0, 1.0]),
            top_ks=jnp.asarray([0, 1, 0, 0]),
            top_ps=jnp.asarray([2.0, 2.0, 0.5, 0.85]),
        )
        for row in range(4):
            seen[row].add(int(toks[row]))
    assert seen[0] == {0}  # temperature 0: greedy
    assert seen[1] == {0}  # top_k=1
    assert seen[2] == {0}  # 0.5 nucleus holds only the 0.6 token
    assert seen[3] == {0, 1}  # 0.85 nucleus: top two, never the tail


def test_runtime_sampler_matches_static_greedy(setup):
    """Runtime sampler and the static `_sample_from_logits` agree on the
    greedy path over real model logits."""
    from bpe_transformer_tpu.models.decode import _sample_from_logits

    params, prompts = setup
    rng = np.random.default_rng(5)
    logits = jnp.asarray(rng.standard_normal((6, CFG.vocab_size)), jnp.float32)
    static = _sample_from_logits(
        logits, jax.random.PRNGKey(0), temperature=0.0, top_k=None
    )
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(6))
    runtime = sample_tokens(
        logits, keys,
        temps=jnp.zeros(6),
        top_ks=jnp.zeros(6, jnp.int32),
        top_ps=jnp.full(6, 2.0),
    )
    np.testing.assert_array_equal(np.asarray(static), np.asarray(runtime))


# --------------------------------------------------------------- scheduler


def test_scheduler_queue_full_rejects():
    sched = FifoScheduler(max_queue=2)
    sched.submit("a", request_id="a")
    sched.submit("b", request_id="b")
    with pytest.raises(QueueFullError):
        sched.submit("c", request_id="c")
    # Draining frees capacity again.
    assert [q.item for q in sched.pop_ready(2).admit] == ["a", "b"]
    sched.submit("c", request_id="c")
    assert sched.depth == 1


def test_scheduler_deadline_and_cancel():
    now = [0.0]
    sched = FifoScheduler(max_queue=8, clock=lambda: now[0])
    sched.submit("a", request_id="a", deadline_s=5.0)
    sched.submit("b", request_id="b")
    assert sched.cancel("b")
    assert not sched.cancel("b")  # already cancelled
    now[0] = 6.0
    pop = sched.pop_ready(4)
    assert [q.request_id for q in pop.expired] == ["a"]
    assert [q.request_id for q in pop.cancelled] == ["b"]
    assert pop.admit == [] and sched.depth == 0


def test_scheduler_max_wait_batches_idle_admissions():
    """With the engine idle, admission holds inside the max-wait window to
    batch prefills — and releases when the window closes or the batch can
    fill every free slot."""
    now = [0.0]
    sched = FifoScheduler(max_queue=8, max_wait_s=2.0, clock=lambda: now[0])
    sched.submit("a", request_id="a")
    assert sched.pop_ready(4, engine_idle=True).admit == []  # window open
    now[0] = 1.0
    sched.submit("b", request_id="b")
    # A running engine never waits.
    assert len(sched.pop_ready(4, engine_idle=False).admit) == 2
    # Window expiry flushes.
    sched.submit("c", request_id="c")
    now[0] = 4.0
    assert len(sched.pop_ready(4, engine_idle=True).admit) == 1
    # A full batch flushes immediately, window or not.
    sched.submit("d", request_id="d")
    sched.submit("e", request_id="e")
    assert len(sched.pop_ready(2, engine_idle=True).admit) == 2


# ------------------------------------------------------ serving layer


def test_streaming_iterator_and_backpressure(setup):
    params, prompts = setup
    with ServingEngine(params, CFG, slots=2, min_bucket=8) as serving:
        handle = serving.submit(
            Request(
                prompt_ids=tuple(prompts[1]),
                max_new_tokens=6,
                temperature=0.0,
            )
        )
        streamed = list(handle.tokens())
        assert streamed == list(handle.result(timeout=30).token_ids)
        assert len(streamed) == 6

        # Queue of 1 + occupied slots -> a burst must hit QueueFullError.
        serving.scheduler.max_queue = 1
        seen_full = False
        handles = []
        for seed in range(12):
            try:
                handles.append(
                    serving.submit(
                        Request(
                            prompt_ids=tuple(prompts[0]),
                            max_new_tokens=24,
                            seed=seed,
                        )
                    )
                )
            except QueueFullError:
                seen_full = True
                break
        assert seen_full, "queue never filled — backpressure untested"
        for h in handles:
            h.result(timeout=60)


def test_deadline_and_cancel_results(setup):
    params, prompts = setup
    serving = ServingEngine(params, CFG, slots=1, min_bucket=8)
    # Not started: deadline/cancel paths exercised deterministically by
    # driving the worker loop by hand.
    serving._running = True
    expired = serving.submit(
        Request(prompt_ids=(1, 2), max_new_tokens=4, deadline_s=0.0)
    )
    cancelled = serving.submit(
        Request(prompt_ids=(3, 4), max_new_tokens=4)
    )
    assert serving.cancel(cancelled.request_id)
    time.sleep(0.01)  # let the zero-deadline lapse
    serving._step()
    assert expired.result(timeout=5).finish_reason == "deadline"
    assert cancelled.result(timeout=5).finish_reason == "cancelled"
    assert expired.result().token_ids == ()


@pytest.mark.slow  # 870s tier-1 budget (PR 11 sweep; ISSUE 11 tooling guard) — runs in the full matrix
def test_drain_finishes_inflight_and_rejects_new(setup):
    """Graceful shutdown (the serve SIGTERM path): drain() stops admission
    but every already-submitted request runs to completion — preemption
    must not cancel work the engine can still finish."""
    params, prompts = setup
    with ServingEngine(params, CFG, slots=2, min_bucket=8) as serving:
        handles = [
            serving.submit(
                Request(prompt_ids=tuple(p), max_new_tokens=6,
                        temperature=0.0)
            )
            for p in prompts[:3]
        ]
        assert serving.drain(timeout_s=60.0)
        for handle in handles:
            result = handle.result(timeout=5)
            assert result.finish_reason in ("length", "stop")
            assert result.token_ids
        with pytest.raises(RuntimeError, match="draining"):
            serving.submit(Request(prompt_ids=(1, 2), max_new_tokens=2))
    # An idle engine drains immediately even with a zero timeout.
    with ServingEngine(params, CFG, slots=1, min_bucket=8) as idle:
        assert idle.drain(timeout_s=0.0)


def test_worker_death_unblocks_all_callers(setup, monkeypatch):
    """An engine failure mid-loop must fail every registered request
    ("error") instead of leaving callers parked on done.wait() forever,
    and subsequent submits must raise instead of silently queueing."""
    params, prompts = setup
    serving = ServingEngine(params, CFG, slots=2, min_bucket=8)
    monkeypatch.setattr(
        serving.engine, "admit",
        lambda *a, **k: (_ for _ in ()).throw(RuntimeError("chip on fire")),
    )
    serving.start()
    handles = [
        serving.submit(Request(prompt_ids=tuple(prompts[0]), max_new_tokens=4))
    ]
    try:
        for _ in range(8):  # the worker may die before later submits land
            handles.append(
                serving.submit(
                    Request(prompt_ids=tuple(prompts[0]), max_new_tokens=4)
                )
            )
    except RuntimeError:
        pass
    for handle in handles:
        assert handle.result(timeout=10).finish_reason == "error"
    with pytest.raises(RuntimeError, match="worker died"):
        serving.submit(Request(prompt_ids=(1, 2), max_new_tokens=2))
    serving.close()


def test_submit_failure_unregisters_entry(setup):
    """A bad deadline value fails enqueue — and must not leak the entry."""
    params, prompts = setup
    serving = ServingEngine(params, CFG, slots=1, min_bucket=8)
    serving._running = True
    with pytest.raises(TypeError):
        serving.submit(
            Request(prompt_ids=(1, 2), max_new_tokens=2, deadline_s="5")
        )
    assert serving._entries == {}


def test_serving_telemetry_stream_and_report(setup):
    """The serving run emits queue_wait/prefill/decode spans + engine
    records into the PR-1 telemetry stream, and `bpe-tpu report` renders a
    serving section from them."""
    params, prompts = setup
    records = []
    telemetry = Telemetry(sink=records.append)
    with ServingEngine(
        params, CFG, slots=2, min_bucket=8,
        telemetry=telemetry, engine_record_every_s=0.0,
    ) as serving:
        serving.run_batch(prompts[:3], max_new_tokens=5, temperature=0.0)

    paths = {r.get("path") for r in records if r.get("kind") == "span"}
    assert {"serve/queue_wait", "serve/prefill", "serve/decode"} <= paths
    engines = [r for r in records if r.get("kind") == "engine"]
    assert engines and all("tokens_per_sec" in r for r in engines)
    # kind="resources" records ride the engine-record cadence: non-null
    # host RSS, an int compile counter, HBM keys present (null on CPU).
    resources = [r for r in records if r.get("kind") == "resources"]
    assert resources, "no resources records in the serving stream"
    for r in resources:
        assert r["host_rss_bytes"] > 0
        assert isinstance(r["compile_events"], int)
        assert "hbm_bytes_in_use" in r
    footer = records[-1]
    assert footer["kind"] == "footer" and footer["clean"] is True
    assert footer["requests"] == 3

    summary = summarize(records)
    assert summary["serving"]["requests"] == 3
    assert summary["serving"]["phases"]["decode"]["n"] == 3
    report = render_report(records)
    assert "== serving ==" in report and "queue_wait" in report


def test_report_serving_fixture_pinned():
    """Committed-fixture smoke: the serving stream schema `bpe-tpu report`
    understands is pinned by tests/fixtures/serving_tiny.jsonl."""
    from bpe_transformer_tpu.telemetry.report import load_records

    records = load_records(REPO / "tests" / "fixtures" / "serving_tiny.jsonl")
    report = render_report(records)
    assert "kind=serve" in report
    assert "== serving ==" in report
    assert "requests 3" in report and "compiled_programs 4" in report
    assert "tokens/sec mean 233.333  (peak 250)" in report
    assert "decode      n=3    p50 1.3s  p95 2.2s  p99 2.2s  max 2.2s" in report
    assert "slow tail dominated by decode" in report
    assert "anomalies (0)" in report and "clean footer" in report


def test_offline_batch_file_mode(tmp_path, setup):
    params, _ = setup
    tokenizer = _byte_tokenizer()
    prompts_path = tmp_path / "prompts.txt"
    prompts_path.write_text("ab\ncdef\n\nxy\n", encoding="utf-8")
    out_path = tmp_path / "completions.jsonl"
    with ServingEngine(
        params, CFG, tokenizer=tokenizer, slots=2, min_bucket=8
    ) as serving:
        results = serving.serve_batch_file(
            prompts_path, out_path, max_new_tokens=4, temperature=0.0
        )
    lines = [json.loads(ln) for ln in out_path.read_text().splitlines()]
    assert [ln["prompt"] for ln in lines] == ["ab", "cdef", "xy"]
    assert len(results) == 3
    for ln in lines:
        assert ln["finish_reason"] == "length" and ln["n_tokens"] == 4
        assert isinstance(ln["completion"], str)
        assert ln["decode_s"] >= 0.0


# ------------------------------------------------- live metrics (tentpole)


def _parse_prom(text: str) -> dict:
    from bpe_transformer_tpu.telemetry.monitor import parse_prometheus

    return parse_prometheus(text)


def test_stats_and_statusz_offline_surface(setup):
    """ServingEngine.stats() exposes the same counters /metrics renders
    (submitted/rejected/finish-reason tallies, phase percentiles) and
    statusz() the operator page (manifest, uptime, compile accounting,
    per-slot state, error ring) — all without any HTTP server.  One engine
    serves both checks: per-engine jit caches make engines the expensive
    resource in this module."""
    params, prompts = setup
    manifest = {"kind": "manifest", "run_kind": "serve", "host": "test"}
    with ServingEngine(
        params, CFG, slots=1, min_bucket=8, manifest=manifest
    ) as serving:
        serving.generate(prompts[0], max_new_tokens=3, temperature=0.0)
        serving.scheduler.max_queue = 1
        rejected = 0
        handles = []
        for seed in range(6):
            try:
                handles.append(
                    serving.submit(
                        Request(
                            prompt_ids=tuple(prompts[0]),
                            max_new_tokens=8,
                            seed=seed,
                        )
                    )
                )
            except QueueFullError:
                rejected += 1
        for h in handles:
            h.result(timeout=60)
        stats = serving.stats()
        page = serving.statusz()
    assert rejected >= 1, "queue never filled — rejection counter untested"
    assert stats["requests_rejected"] == rejected
    assert stats["requests_submitted"] == 1 + len(handles)
    assert stats["finish_reasons"]["length"] == 1 + len(handles)
    assert stats["finish_reasons"]["error"] == 0
    assert stats["uptime_s"] > 0
    assert stats["phase_p95_s"]["decode"] is not None

    assert page["manifest"] is manifest
    assert page["uptime_s"] > 0
    # Router-facing health fields (serving/router.py reads these).
    assert page["engine_kind"] == "dense" and stats["engine_kind"] == "dense"
    assert page["draining"] is False
    assert page["slots"] == 1 and page["active_slots"] == 0
    assert "kvpool" not in page  # dense engines carry no kv gauges
    assert page["compiled_programs"] >= 1
    assert isinstance(page["compile_events"], int)
    assert page["compile_events"] >= page["compiled_programs"]
    assert len(page["slot_states"]) == 1
    assert page["slot_states"][0]["slot"] == 0
    assert page["last_errors"] == []
    assert page["resources"]["host_rss_bytes"] > 0
    json.dumps(page)  # the whole page must be one JSON document


def test_statusz_records_worker_error(setup, monkeypatch):
    params, prompts = setup
    serving = ServingEngine(params, CFG, slots=1, min_bucket=8)
    monkeypatch.setattr(
        serving.engine, "admit",
        lambda *a, **k: (_ for _ in ()).throw(RuntimeError("chip on fire")),
    )
    serving.start()
    handle = serving.submit(
        Request(prompt_ids=tuple(prompts[0]), max_new_tokens=2)
    )
    assert handle.result(timeout=10).finish_reason == "error"
    errors = serving.statusz()["last_errors"]
    assert errors and "chip on fire" in errors[-1]["error"]
    assert serving.stats()["finish_reasons"]["error"] >= 1
    serving.close()


def test_metrics_endpoint_prometheus_exposition(setup):
    """ACCEPTANCE: GET /metrics returns valid Prometheus text exposition —
    counters monotone across scrapes, histograms sum-consistent (+Inf
    bucket == _count, bucket counts nondecreasing in le) — and /statusz
    returns the manifest + compile counter."""
    params, prompts = setup
    manifest = {"kind": "manifest", "run_kind": "serve", "host": "test"}
    with ServingEngine(
        params, CFG, slots=2, min_bucket=8, manifest=manifest
    ) as serving:
        serving.generate(prompts[0], max_new_tokens=3, temperature=0.0)
        server = make_http_server(serving, port=0)
        port = server.server_address[1]
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        try:
            base = f"http://127.0.0.1:{port}"

            def scrape():
                resp = urllib.request.urlopen(f"{base}/metrics", timeout=30)
                assert resp.headers["Content-Type"].startswith("text/plain")
                return _parse_prom(resp.read().decode())

            first = scrape()
            assert first["bpe_tpu_requests_submitted_total"] == 1
            assert first['bpe_tpu_requests_finished_total{reason="length"}'] == 1
            assert first["bpe_tpu_tokens_generated_total"] == 3
            assert first["bpe_tpu_engine_compiled_programs"] >= 1

            serving.generate(prompts[1], max_new_tokens=4, temperature=0.0)
            second = scrape()
            # Counters are monotone between scrapes.
            for name in (
                "bpe_tpu_requests_submitted_total",
                "bpe_tpu_tokens_generated_total",
                "bpe_tpu_ticks_total",
                'bpe_tpu_requests_finished_total{reason="length"}',
            ):
                assert second[name] >= first[name], name
            assert second["bpe_tpu_requests_submitted_total"] == 2
            assert second["bpe_tpu_tokens_generated_total"] == 7

            # Histogram consistency per phase: buckets cumulative/monotone,
            # +Inf bucket equals _count, _sum nonnegative.
            for phase in ("queue_wait", "prefill", "decode"):
                buckets = sorted(
                    (
                        (float(name.split('le="')[1].rstrip('"}').replace(
                            "+Inf", "inf")), value)
                        for name, value in second.items()
                        if name.startswith(
                            "bpe_tpu_request_phase_seconds_bucket"
                        )
                        and f'phase="{phase}"' in name
                    ),
                )
                counts = [v for _, v in buckets]
                assert counts == sorted(counts), f"{phase}: non-cumulative"
                count = second[
                    f'bpe_tpu_request_phase_seconds_count{{phase="{phase}"}}'
                ]
                assert buckets[-1][1] == count == 2
                assert (
                    second[
                        f'bpe_tpu_request_phase_seconds_sum{{phase="{phase}"}}'
                    ]
                    >= 0
                )

            statusz = json.loads(
                urllib.request.urlopen(f"{base}/statusz", timeout=30).read()
            )
            assert statusz["manifest"]["run_kind"] == "serve"
            assert statusz["compiled_programs"] >= 1
            assert isinstance(statusz["compile_events"], int)
            assert len(statusz["slot_states"]) == 2

            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(f"{base}/nope", timeout=30)
            assert err.value.code == 404
        finally:
            server.shutdown()
            server.server_close()
            thread.join(timeout=10)


# ------------------------------------------------------------------- HTTP


def _byte_tokenizer():
    from bpe_transformer_tpu.tokenization import BPETokenizer

    # CFG.vocab_size=128: plain ASCII byte vocab + one special stop token.
    return BPETokenizer(
        vocab={i: bytes([i]) for i in range(127)},
        merges=[],
        special_tokens=["<|eot|>"],  # appended as id 127
    )


def _post_json(url: str, payload: dict, timeout: float = 60.0) -> dict:
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode("utf-8"),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def test_http_endpoint_roundtrip_and_errors(setup):
    """In-process HTTP: generate + healthz + 400 on bad input, all
    timeout-bounded."""
    params, _ = setup
    tokenizer = _byte_tokenizer()
    with ServingEngine(
        params, CFG, tokenizer=tokenizer, slots=2, min_bucket=8,
        default_max_new_tokens=5,
    ) as serving:
        server = make_http_server(serving, port=0)
        port = server.server_address[1]
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        try:
            base = f"http://127.0.0.1:{port}"
            out = _post_json(
                f"{base}/generate",
                {"prompt": "ab", "temperature": 0.0, "max_new_tokens": 4},
            )
            assert len(out["token_ids"]) == 4
            assert out["finish_reason"] in ("length", "stop")
            assert "completion" in out
            assert out["timings"]["decode_s"] >= 0.0

            health = json.loads(
                urllib.request.urlopen(f"{base}/healthz", timeout=30).read()
            )
            assert health["ok"] and health["slots"] == 2
            assert health["requests_finished"] >= 1

            # Per-request stop_id: the stop token never reaches the
            # rendered completion (ids keep it; prose drops it).
            sid = out["token_ids"][0]
            stopped = _post_json(
                f"{base}/generate",
                {
                    "prompt": "ab", "temperature": 0.0,
                    "max_new_tokens": 4, "stop_id": sid,
                },
            )
            assert stopped["finish_reason"] == "stop"
            assert stopped["token_ids"][-1] == sid
            assert stopped["completion"] == serving.tokenizer.decode(
                stopped["token_ids"][:-1]
            )

            with pytest.raises(urllib.error.HTTPError) as err:
                _post_json(f"{base}/generate", {"bogus": 1})
            assert err.value.code == 400
        finally:
            server.shutdown()
            server.server_close()
            thread.join(timeout=10)


def test_cli_serve_http_smoke(tmp_path, setup):
    """ACCEPTANCE: `bpe-tpu serve` end-to-end on CPU — HTTP round-trip of a
    generate request returns a completion, and the telemetry stream's
    queue_wait/prefill/decode spans are visible in `bpe-tpu report`.
    Timeout-bounded at every step so tier-1 stays fast."""
    from bpe_transformer_tpu.checkpointing import save_checkpoint

    params, _ = setup
    ckpt = tmp_path / "model.ckpt"
    save_checkpoint(
        ckpt,
        params=params,
        extra={"model_config": dataclasses.asdict(CFG)},
    )
    tok_dir = tmp_path / "tok"
    tok_dir.mkdir()
    with open(tok_dir / "vocab.pkl", "wb") as f:
        pickle.dump({i: bytes([i]) for i in range(127)}, f)
    with open(tok_dir / "merges.pkl", "wb") as f:
        pickle.dump([], f)
    metrics = tmp_path / "serve_metrics.jsonl"

    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PYTHONPATH=str(REPO),
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
    )
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "bpe_transformer_tpu.training.cli",
            "serve",
            "--checkpoint", str(ckpt),
            "--tokenizer-dir", str(tok_dir),
            "--special-token", "<|eot|>",
            "--port", "0",
            "--slots", "2",
            "--max-new-tokens", "6",
            "--metrics-jsonl", str(metrics),
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        cwd=tmp_path,
        env=env,
    )
    # Hard kill switch: a hung jax boot must fail THIS test, not stall the
    # whole tier-1 run (readline would otherwise block unbounded).
    killer = threading.Timer(240, proc.kill)
    killer.start()
    try:
        # Wait (bounded) for the "serving on http://..." banner.
        port = None
        deadline = time.monotonic() + 240
        line = ""
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                assert proc.poll() is None, (
                    f"serve exited early: {proc.stderr.read()}"
                )
                continue
            if line.startswith("serving on http://"):
                port = int(line.split("http://", 1)[1].split()[0].rsplit(":", 1)[1])
                break
        assert port, f"no serving banner (last line: {line!r})"

        out = _post_json(
            f"http://127.0.0.1:{port}/generate",
            {"prompt": "ab", "temperature": 0.0},
            timeout=120,
        )
        assert out["finish_reason"] in ("length", "stop")
        assert len(out["token_ids"]) >= 1
        assert isinstance(out["completion"], str)

        # The live observability surface on a real `bpe-tpu serve` process:
        # Prometheus /metrics and the /statusz operator page.
        prom = _parse_prom(
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=60
            ).read().decode()
        )
        assert prom["bpe_tpu_requests_submitted_total"] >= 1
        assert prom["bpe_tpu_tokens_generated_total"] >= 1
        statusz = json.loads(
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/statusz", timeout=60
            ).read()
        )
        assert statusz["manifest"]["run_kind"] == "serve"
        assert statusz["compiled_programs"] >= 1
    finally:
        killer.cancel()
        proc.send_signal(signal.SIGINT)
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)

    # The stream a serve run leaves behind is report-readable and carries
    # the per-request spans.
    report = subprocess.run(
        [
            sys.executable, "-m", "bpe_transformer_tpu.training.cli",
            "report", str(metrics),
        ],
        capture_output=True,
        text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=str(REPO)),
        timeout=120,
    )
    assert report.returncode == 0, report.stderr
    assert "kind=serve" in report.stdout
    assert "== serving ==" in report.stdout
    for phase in ("serve/queue_wait", "serve/prefill", "serve/decode"):
        assert phase in report.stdout, report.stdout


# -------------------------------- per-request traces & per-bucket metrics


def test_per_bucket_prefill_and_decode_throughput_metrics(setup):
    """/metrics grows per-bucket prefill token-throughput, cumulative
    decode throughput, and a compile-time gauge; stats() carries the same
    aggregates (prefill_bucket_work / decode_*) without shadowing the
    engine's bucket-ladder list.  A bucket's compile-paying first
    admission is counted as a request + compile but excluded from the
    throughput accumulator, so the gauge reflects steady-state prefill."""
    params, prompts = setup
    with ServingEngine(params, CFG, slots=2, min_bucket=8) as serving:
        serving.generate(prompts[0], max_new_tokens=3, temperature=0.0)  # b=8, cold
        serving.generate(prompts[1], max_new_tokens=3, temperature=0.0)  # b=8, warm
        serving.generate(prompts[2], max_new_tokens=3, temperature=0.0)  # b=16, cold
        stats = serving.stats()
        prom = _parse_prom(serving.prometheus_metrics())

    # The bucket-ladder list survives (no key shadowing by the snapshot).
    assert stats["prefill_buckets"] == [8, 16, 32]
    work = stats["prefill_bucket_work"]
    # Bucket 8: cold + warm — only the warm admission's tokens/seconds
    # enter the throughput accumulator; the cold one shows as a compile.
    assert work[8]["requests"] == 2 and work[8]["compiles"] == 1
    assert work[8]["tokens"] == len(prompts[1])
    assert work[8]["tokens_per_sec"] > 0
    # Bucket 16: only a cold admission so far — no throughput sample yet.
    assert work[16]["requests"] == 1 and work[16]["compiles"] == 1
    assert work[16]["tokens"] == 0 and work[16]["tokens_per_sec"] is None
    assert stats["decode_tokens"] > 0
    assert stats["decode_seconds"] > 0
    assert stats["decode_tokens_per_sec"] > 0

    assert prom['bpe_tpu_prefill_requests_total{bucket="8"}'] == 2
    assert prom['bpe_tpu_prefill_compiles_total{bucket="8"}'] == 1
    assert prom['bpe_tpu_prefill_tokens_total{bucket="8"}'] == len(prompts[1])
    assert prom['bpe_tpu_prefill_tokens_total{bucket="16"}'] == 0
    assert prom['bpe_tpu_prefill_seconds_total{bucket="8"}'] >= 0
    assert prom["bpe_tpu_decode_tokens_total"] > 0
    assert prom["bpe_tpu_decode_seconds_total"] > 0
    assert prom["bpe_tpu_decode_tokens_per_sec"] > 0
    # Cumulative XLA compile time: this engine paid real compiles.
    assert prom["bpe_tpu_compile_time_seconds_total"] > 0


@pytest.mark.slow  # 870s tier-1 budget (PR 11 sweep; ISSUE 11 tooling guard) — runs in the full matrix
def test_statusz_recent_requests_ring_traces_phases(setup):
    """/statusz exposes a per-request trace ring: each finished request's
    queue_wait/prefill/decode timeline with its request_id, bucket, and
    finish reason — the live per-request view (the JSONL spans carry the
    same numbers for the offline one)."""
    params, prompts = setup
    with ServingEngine(params, CFG, slots=2, min_bucket=8) as serving:
        r1 = serving.generate(prompts[0], max_new_tokens=3, temperature=0.0)
        r2 = serving.generate(prompts[2], max_new_tokens=2, temperature=0.0)
        page = serving.statusz()

    recent = page["recent_requests"]
    assert [r["request_id"] for r in recent] == [r1.request_id, r2.request_id]
    first = recent[0]
    assert first["finish_reason"] == "length"
    assert first["n_tokens"] == 3
    assert first["prompt_len"] == len(prompts[0])
    assert first["bucket"] == 8
    assert first["queue_wait_s"] >= 0
    assert first["prefill_s"] > 0
    assert first["decode_s"] >= 0
    # The ring agrees with the Result the caller saw (one measurement,
    # two surfaces).
    assert first["prefill_s"] == pytest.approx(r1.prefill_s, abs=1e-6)
    json.dumps(page)  # statusz stays one JSON document


# ------------------------------------------------ fleet tracing (ISSUE 12)


def test_serve_http_echoes_request_id_on_success_and_error_paths(setup):
    """Satellite pin: X-Request-Id comes back on EVERY serve response —
    success (adopted as the request_id tagging spans/slots), 400 bad
    input, and the draining 503 — so clients correlate failures with
    traces."""
    params, _ = setup
    tokenizer = _byte_tokenizer()
    with ServingEngine(
        params, CFG, tokenizer=tokenizer, slots=2, min_bucket=8,
        default_max_new_tokens=4,
    ) as serving:
        server = make_http_server(serving, port=0)
        port = server.server_address[1]
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        try:
            base = f"http://127.0.0.1:{port}"
            req = urllib.request.Request(
                f"{base}/generate",
                data=json.dumps(
                    {"prompt": "ab", "temperature": 0.0,
                     "max_new_tokens": 3}
                ).encode(),
                headers={"Content-Type": "application/json",
                         "X-Request-Id": "router-trace-42"},
            )
            with urllib.request.urlopen(req, timeout=60) as resp:
                assert resp.headers["X-Request-Id"] == "router-trace-42"
                out = json.loads(resp.read())
            # ADOPTED, not just echoed: the inbound id IS the request id.
            assert out["request_id"] == "router-trace-42"

            # 400 path: bad input still carries the id.
            req = urllib.request.Request(
                f"{base}/generate", data=json.dumps({"bogus": 1}).encode(),
                headers={"Content-Type": "application/json",
                         "X-Request-Id": "bad-input-id"},
            )
            try:
                urllib.request.urlopen(req, timeout=30)
                raise AssertionError("expected 400")
            except urllib.error.HTTPError as err:
                assert err.code == 400
                assert err.headers["X-Request-Id"] == "bad-input-id"
                assert json.loads(err.read())["request_id"] == "bad-input-id"

            # Headerless requests get a minted id (echo always holds).
            req = urllib.request.Request(
                f"{base}/generate", data=json.dumps({"bogus": 1}).encode(),
                headers={"Content-Type": "application/json"},
            )
            try:
                urllib.request.urlopen(req, timeout=30)
                raise AssertionError("expected 400")
            except urllib.error.HTTPError as err:
                assert len(err.headers["X-Request-Id"]) == 32

            # 503 path: drain stops admission; the rejection is traceable.
            assert serving.drain(timeout_s=30)
            req = urllib.request.Request(
                f"{base}/generate",
                data=json.dumps({"prompt": "ab"}).encode(),
                headers={"Content-Type": "application/json",
                         "X-Request-Id": "drained-id"},
            )
            try:
                urllib.request.urlopen(req, timeout=30)
                raise AssertionError("expected 503")
            except urllib.error.HTTPError as err:
                assert err.code == 503
                assert err.headers["X-Request-Id"] == "drained-id"
        finally:
            server.shutdown()
            server.server_close()
            thread.join(timeout=10)


def test_engines_carry_request_id_and_request_level_histograms(setup):
    """The engines adopt the request id as slot metadata (statusz slot
    table names the occupying request), and the metrics layer grows the
    request-level ttfb/total histograms the fleet SLO layer counts from."""
    params, prompts = setup
    engine = SlotPoolEngine(params, CFG, slots=2, min_bucket=8)
    event = engine.admit(
        prompts[0], max_new_tokens=8, request_id="rid-slot-1"
    )
    state = next(s for s in engine.slot_states() if s["active"])
    assert state["request_id"] == "rid-slot-1"
    engine.release(event.slot)

    with ServingEngine(params, CFG, slots=1, min_bucket=8) as serving:
        result = serving.generate(
            prompts[0], max_new_tokens=3, temperature=0.0,
            request_id="rid-gen-1",
        )
        assert result.request_id == "rid-gen-1"
        stats = serving.stats()
        # Request-level histograms observed exactly once per request.
        assert stats["phase_p50_s"]["ttfb"] is not None
        assert stats["phase_p50_s"]["total"] is not None
        prom = serving.prometheus_metrics()
        assert 'phase="ttfb"' in prom and 'phase="total"' in prom
        assert "bpe_tpu_alerts_firing 0" in prom
        # Duplicate in-flight ids are refused (the id keys the trace).
        handle = serving.submit(
            Request(prompt_ids=tuple(prompts[0]), max_new_tokens=32,
                    request_id="dup-id")
        )
        with pytest.raises(ValueError, match="already in flight"):
            serving.submit(
                Request(prompt_ids=tuple(prompts[0]), max_new_tokens=4,
                        request_id="dup-id")
            )
        handle.result(timeout=60)


def test_serving_block_exhaustion_alert_fires_and_clears(setup):
    """ACCEPTANCE (watchdog, engine side): a real paged engine whose
    block pool drains across watchdog samples fires the exhaustion alert
    — visible in statusz and as kind=alert records — and the alert
    clears when retirements refill the pool."""
    from bpe_transformer_tpu.telemetry.alerts import BlockExhaustionRule

    params, prompts = setup
    records = []

    class _Sink:
        def emit(self, record):
            records.append(record)

    serving = ServingEngine(
        params, CFG, slots=4, min_bucket=8, paged=True, block_size=4,
        num_kv_blocks=24, prefix_cache=False,
        alert_rules=[BlockExhaustionRule(window=3, horizon_s=1e9)],
        telemetry=_Sink(),
    )
    # Drive the watchdog directly (no worker): each begin() reserves the
    # request's worst-case block chain, so admissions ARE the drain.
    serving._feed_alerts(0.0, None)
    slots = []
    for t, prompt in enumerate(prompts[:2], start=1):
        slots.append(
            serving.engine.begin(prompt[:4], max_new_tokens=8)
        )
        serving._feed_alerts(float(t), None)
    page = serving.statusz()
    assert [a["rule"] for a in page["alerts"]] == ["block_exhaustion"]
    assert page["alerts"][0]["projected_dry_s"] > 0
    assert serving.stats()["alerts_firing"] == 1
    firing = [r for r in records if r.get("kind") == "alert"]
    assert [r["state"] for r in firing] == ["firing"]

    # Retirements free the blocks: the trend flips and the alert clears.
    for slot in slots:
        serving.engine.release(slot)
    serving._feed_alerts(3.0, None)
    assert serving.statusz()["alerts"] == []
    alert_states = [
        r["state"] for r in records if r.get("kind") == "alert"
    ]
    assert alert_states == ["firing", "cleared"]


def test_watchdog_compile_rule_fed_without_telemetry_sink(setup):
    """Regression: the compile counter must reach the watchdog even on a
    server run with NO --metrics-jsonl — resources are sampled on the
    record cadence unconditionally, so a compile storm is visible in
    /statusz alerts with no telemetry sink attached."""
    from bpe_transformer_tpu.telemetry.alerts import CompileStormRule

    params, _ = setup
    rule = CompileStormRule(window=2, min_compiles=0)
    serving = ServingEngine(
        params, CFG, slots=1, min_bucket=8,
        alert_rules=[rule], engine_record_every_s=0.0,
    )
    assert serving._telemetry is None
    serving._maybe_emit_engine_record()
    serving._maybe_emit_engine_record()
    # min_compiles=0: any two samples fire IF compile_events reached the
    # rule — which is the thing under test.
    assert len(rule._hist) == 2
    assert all(isinstance(n, int) for n in rule._hist)
    assert [a["rule"] for a in serving._alerts.active()] == [
        "compile_storm"
    ]


def test_duplicate_inflight_request_id_is_retryable_503(setup):
    """Regression: a client retrying a router 504 keeps its echoed
    X-Request-Id — hitting the replica still running the original must
    503 (router fails over to a peer), never 400 (which the router would
    pass through as the CALLER's fault without trying anyone else)."""
    params, prompts = setup
    with ServingEngine(params, CFG, slots=1, min_bucket=8) as serving:
        server = make_http_server(serving, port=0)
        port = server.server_address[1]
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        try:
            handle = serving.submit(
                Request(prompt_ids=tuple(prompts[0]), max_new_tokens=24,
                        request_id="retry-trace-1")
            )
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate",
                data=json.dumps(
                    {"prompt_ids": prompts[0], "max_new_tokens": 2}
                ).encode(),
                headers={"Content-Type": "application/json",
                         "X-Request-Id": "retry-trace-1"},
            )
            try:
                urllib.request.urlopen(req, timeout=30)
                raise AssertionError("expected 503")
            except urllib.error.HTTPError as err:
                assert err.code == 503
                assert err.headers["X-Request-Id"] == "retry-trace-1"
                assert "already in flight" in json.loads(
                    err.read()
                )["error"]
            handle.result(timeout=120)
        finally:
            server.shutdown()
            server.server_close()
            thread.join(timeout=10)


# --------------------------------------- KV migration serving (ISSUE 15)


def test_drain_evacuation_migrates_sessions_zero_failures(setup):
    """ACCEPTANCE (ISSUE 15): two in-process replicas under load — drain
    one mid-generation with evacuation peers and every session migrates:
    zero failed/cancelled requests, and the tokens are identical to the
    same requests served by an undisturbed replica (extends the PR 8
    drain test from finish-in-place to finish-elsewhere)."""
    from bpe_transformer_tpu.telemetry import Telemetry, validate_record

    params, prompts = setup
    records_a: list = []
    records_b: list = []
    kwargs = dict(slots=4, min_bucket=8, paged=True, block_size=8)
    ref = {}
    with ServingEngine(params, CFG, **kwargs) as mono:
        for i, p in enumerate(prompts):
            ref[i] = mono.generate(
                p, max_new_tokens=20, temperature=0.8, seed=i
            ).token_ids
    a = ServingEngine(
        params, CFG, telemetry=Telemetry(sink=records_a.append), **kwargs
    )
    b = ServingEngine(
        params, CFG, telemetry=Telemetry(sink=records_b.append), **kwargs
    )
    with a, b:
        handles = [
            a.submit(
                Request(prompt_ids=tuple(p), max_new_tokens=20,
                        temperature=0.8, seed=i)
            )
            for i, p in enumerate(prompts)
        ]
        time.sleep(0.2)  # let generations get genuinely mid-flight
        assert a.drain(timeout_s=120.0, evacuate_to=[b]), "drain timed out"
        results = [h.result(timeout=120) for h in handles]
        for i, result in enumerate(results):
            assert result.finish_reason in ("stop", "length"), result
            assert result.token_ids == ref[i], (
                f"request {i} diverged after evacuation"
            )
        assert a.stats()["migrations_out"] + b.stats()["migrations_in"] > 0
        # Evacuated sessions seed the peer: nothing remains on A.
        assert a.engine.active_count == 0

    evac = [r for r in records_a if r.get("kind") == "migration"]
    grafts = [r for r in records_b if r.get("kind") == "migration"]
    assert any(r["direction"] == "evacuate" for r in evac)
    assert any(r["direction"] == "import" for r in grafts)
    for record in evac + grafts:
        assert validate_record(record) == [], record
    assert any(
        r.get("kind") == "span" and r.get("path") == "serve/migration_import"
        for r in records_b
    )


def test_prefill_role_and_kv_http_endpoints(setup):
    """ACCEPTANCE (ISSUE 15 tentpole, HTTP surface): POST /kv/export on a
    prefill-role replica returns the finished prefix as a binary payload
    (X-Request-Id echoed); POST /kv/import on a decode-role replica
    grafts it and answers with the full generation, token-identical to
    the monolithic run; plain /generate on the prefill replica is a 503;
    the decode replica fed only imports stays within tick + inject."""
    from bpe_transformer_tpu.telemetry.monitor import parse_prometheus

    params, prompts = setup
    prompt = prompts[3]
    with ServingEngine(
        params, CFG, slots=2, min_bucket=8, paged=True, block_size=8
    ) as mono:
        ref = mono.generate(
            prompt, max_new_tokens=8, temperature=0.7, seed=9
        ).token_ids

    pre = ServingEngine(params, CFG, slots=2, min_bucket=8, paged=True,
                        block_size=8, role="prefill")
    dec = ServingEngine(params, CFG, slots=2, min_bucket=8, paged=True,
                        block_size=8, role="decode")
    servers, threads, ports = [], [], []
    for s in (pre, dec):
        s.start()
        srv = make_http_server(s, port=0)
        ports.append(srv.server_address[1])
        th = threading.Thread(target=srv.serve_forever, daemon=True)
        th.start()
        servers.append(srv)
        threads.append(th)
    try:
        body = json.dumps(
            {"prompt_ids": prompt, "max_new_tokens": 8,
             "temperature": 0.7, "seed": 9, "deadline_s": 90.0}
        ).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{ports[0]}/kv/export", data=body,
            headers={"Content-Type": "application/json",
                     "X-Request-Id": "mig-trace-1"},
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            assert resp.headers["Content-Type"] == "application/octet-stream"
            assert resp.headers["X-Request-Id"] == "mig-trace-1"
            payload = resp.read()
        assert payload.startswith(b"BPEKV")
        from bpe_transformer_tpu.serving.kvpool.migrate import (
            payload_from_bytes,
        )

        meta = payload_from_bytes(payload)["meta"]
        # The serving contract rides the payload: the client's deadline
        # survives the migration and the import side can report the full
        # export/transfer/import split.
        assert meta["deadline_s"] == 90.0
        assert isinstance(meta["export_s"], float)
        assert meta["emitted"], "the sampled first token rides the payload"

        # The prefill replica refuses a plain generation: 503, failover.
        req = urllib.request.Request(
            f"http://127.0.0.1:{ports[0]}/generate", data=body,
            headers={"Content-Type": "application/json"},
        )
        try:
            urllib.request.urlopen(req, timeout=30)
            raise AssertionError("expected 503 from the prefill role")
        except urllib.error.HTTPError as err:
            assert err.code == 503
            assert "prefill-role" in json.loads(err.read())["error"]

        req = urllib.request.Request(
            f"http://127.0.0.1:{ports[1]}/kv/import", data=payload,
            headers={"Content-Type": "application/octet-stream"},
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            out = json.loads(resp.read())
        assert resp.headers["X-Request-Id"] == "mig-trace-1"
        assert out["request_id"] == "mig-trace-1"
        assert tuple(out["token_ids"]) == ref
        assert out["finish_reason"] in ("stop", "length")

        # Compile bound: the decode replica has served ONLY the graft —
        # tick + inject, no chunk ladder (the acceptance assertion).
        assert dec.engine.compiled_programs() <= 2
        assert dec.stats()["role"] == "decode"
        assert dec.stats()["migrations_in"] == 1
        assert pre.stats()["migrations_out"] == 1
        prom = parse_prometheus(pre.prometheus_metrics())
        assert prom["bpe_tpu_migrations_out_total"] == 1
        assert prom['bpe_tpu_replica_role{role="prefill"}'] == 1
        assert pre.statusz()["role"] == "prefill"

        # A corrupted payload is a 400 (geometry/format guard), not a 500.
        req = urllib.request.Request(
            f"http://127.0.0.1:{ports[1]}/kv/import", data=payload[:40],
            headers={"Content-Type": "application/octet-stream"},
        )
        try:
            urllib.request.urlopen(req, timeout=30)
            raise AssertionError("expected 400")
        except urllib.error.HTTPError as err:
            assert err.code == 400
    finally:
        for srv in servers:
            srv.shutdown()
            srv.server_close()
        for th in threads:
            th.join(timeout=10)
        pre.close()
        dec.close()


def test_kv_import_idempotency_key_grafts_exactly_once(setup):
    """ACCEPTANCE (ISSUE 20): a retried /kv/import carrying the same
    X-Idempotency-Key grafts exactly once — the retry attaches to the
    original graft and resolves with ITS result (token-identical), and
    migrations_in counts one move.  A different key is a different
    transfer and grafts again."""
    from bpe_transformer_tpu.serving.kvpool.migrate import (
        payload_from_bytes,
        payload_to_bytes,
    )

    params, prompts = setup
    prompt = prompts[2]
    with ServingEngine(
        params, CFG, slots=2, min_bucket=8, paged=True, block_size=8
    ) as mono:
        ref = mono.generate(
            prompt, max_new_tokens=8, temperature=0.0
        ).token_ids

    serving = ServingEngine(
        params, CFG, slots=2, min_bucket=8, paged=True, block_size=8
    )
    serving.start()
    server = make_http_server(serving, port=0)
    port = server.server_address[1]
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        base = f"http://127.0.0.1:{port}"
        body = json.dumps(
            {"prompt_ids": prompt, "max_new_tokens": 8,
             "temperature": 0.0}
        ).encode()
        req = urllib.request.Request(
            f"{base}/kv/export", data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            payload = resp.read()

        def kv_import(data, key):
            headers = {"Content-Type": "application/octet-stream"}
            if key:
                headers["X-Idempotency-Key"] = key
            req = urllib.request.Request(
                f"{base}/kv/import", data=data, headers=headers
            )
            with urllib.request.urlopen(req, timeout=120) as resp:
                return json.loads(resp.read())

        first = kv_import(payload, "transfer-1")
        retry = kv_import(payload, "transfer-1")  # the blackholed-retry
        assert tuple(first["token_ids"]) == ref
        assert retry["token_ids"] == first["token_ids"]
        assert retry["request_id"] == first["request_id"]
        assert serving.stats()["migrations_in"] == 1, (
            "a retried import under one idempotency key must graft once"
        )

        # A DIFFERENT key is a new transfer: it grafts independently.
        decoded = payload_from_bytes(payload)
        decoded["meta"]["request_id"] = "transfer-2-rid"
        second = kv_import(payload_to_bytes(decoded), "transfer-2")
        assert second["token_ids"] == first["token_ids"]
        assert serving.stats()["migrations_in"] == 2
    finally:
        server.shutdown()
        server.server_close()
        thread.join(timeout=10)
        serving.close()


def test_role_validation_and_accepting_imports(setup):
    """Role knob guards: non-both roles need the paged engine; migrate
    requests need the paged engine; a prefill-role replica reports it
    does not accept imports."""
    params, _ = setup
    with pytest.raises(ValueError, match="paged"):
        ServingEngine(params, CFG, slots=1, role="decode")
    with pytest.raises(ValueError, match="role"):
        ServingEngine(params, CFG, slots=1, paged=True, role="exporter")
    dense = ServingEngine(params, CFG, slots=1, min_bucket=8)
    dense._running = True
    with pytest.raises(ValueError, match="paged"):
        dense.submit(
            Request(prompt_ids=(1, 2), max_new_tokens=2, migrate=True)
        )
    pre = ServingEngine(params, CFG, slots=1, paged=True, block_size=8,
                        role="prefill")
    pre._running = True
    assert not pre.accepting_imports()
    with pytest.raises(RuntimeError, match="prefill-role"):
        pre.submit(Request(prompt_ids=(1, 2), max_new_tokens=2))


@pytest.mark.slow  # 870s tier-1 budget (PR 14): heavy two-replica E2E matrix — cheap tier-1 siblings above
def test_drain_evacuation_heavy_matrix(setup):
    """Full-matrix drain evacuation (slow; tier-1 siblings:
    test_drain_evacuation_migrates_sessions_zero_failures + the kvpool
    migration pins): int8 pool + chunked prefill + per-tick budget, more
    load than slots, drain fired while some sessions are still
    MID-CHUNKED-PREFILL — every request completes on the peer with
    tokens identical to an undisturbed replica, across greedy and seeded
    sampling."""
    params, prompts = setup
    rng = np.random.default_rng(7)
    long_prompts = [
        [int(t) for t in rng.integers(0, CFG.vocab_size, size=n)]
        for n in (24, 26, 21, 25, 23)
    ]
    load = prompts + long_prompts  # 9 requests over 4 slots
    kwargs = dict(
        slots=4, min_bucket=8, paged=True, block_size=8, kv_dtype="int8",
        prefill_chunk=8, prefill_token_budget=8, max_queue=32,
    )
    knobs = [
        dict(temperature=0.0) if i % 2 else
        dict(temperature=0.9, top_k=9, top_p=0.85)
        for i in range(len(load))
    ]
    ref = {}
    with ServingEngine(params, CFG, **kwargs) as mono:
        for i, p in enumerate(load):
            ref[i] = mono.generate(
                p, max_new_tokens=6, seed=i, **knobs[i]
            ).token_ids
    a = ServingEngine(params, CFG, **kwargs)
    b = ServingEngine(params, CFG, **kwargs)
    with a, b:
        handles = [
            a.submit(
                Request(prompt_ids=tuple(p), max_new_tokens=6, seed=i,
                        **knobs[i])
            )
            for i, p in enumerate(load)
        ]
        # Fire the drain ASAP: with 9 requests, 8-token chunks, and an
        # 8-token/tick budget, several prompts are mid-prefill or still
        # queued when the evacuation sweep runs.
        assert a.drain(timeout_s=180.0, evacuate_to=[b]), "drain timed out"
        for i, handle in enumerate(handles):
            result = handle.result(timeout=180)
            assert result.finish_reason in ("stop", "length"), (i, result)
            assert result.token_ids == ref[i], (
                f"request {i} diverged after int8/chunked evacuation"
            )
        assert a.engine.active_count == 0
        assert b.stats()["migrations_in"] + b.stats()["requests_submitted"] \
            >= len(load)
