"""Smoke-run the examples/ scripts (the reference's notebook equivalents).

Each runs as a subprocess on the small reference fixtures with CPU forced,
asserting exit 0 and the expected closing output.
"""

import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def run_example(tmp_path, sample: Path, script: str, *args: str) -> str:
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
    )
    proc = subprocess.run(
        [sys.executable, str(REPO / "examples" / script), "--input", str(sample), *args],
        capture_output=True,
        text=True,
        cwd=tmp_path,
        env=env,
        timeout=600,
    )
    assert proc.returncode == 0, f"{script} failed:\n{proc.stdout}\n{proc.stderr}"
    return proc.stdout


@pytest.fixture
def sample(reference_fixtures) -> Path:
    return reference_fixtures / "tinystories_sample.txt"


def test_example_pretokenization(tmp_path, sample):
    out = run_example(tmp_path, sample, "1_pretokenization.py", "--workers", "2")
    assert "paths agree" in out


def test_example_train_bpe(tmp_path, sample):
    out = run_example(tmp_path, sample, "2_train_bpe.py", "--vocab-size", "400")
    assert "longest learned token" in out
    assert (tmp_path / "bpe_artifacts" / "vocab.pkl").exists()


def test_example_encode_decode(tmp_path, sample):
    out = run_example(tmp_path, sample, "3_encode_decode.py")
    assert "roundtrip OK" in out


@pytest.mark.slow
def test_example_train_lm(tmp_path, sample):
    out = run_example(
        tmp_path, sample, "4_train_lm.py", "--steps", "4", "--vocab-size", "400"
    )
    assert "4/4  sampling" in out
    assert (tmp_path / "lm_demo" / "checkpoints" / "latest.ckpt").exists()
    assert (tmp_path / "lm_demo" / "metrics.jsonl").exists()


@pytest.mark.slow
def test_example_long_context_sp(tmp_path, sample):
    out = run_example(
        tmp_path, sample, "5_long_context_sp.py",
        "--steps", "6", "--context", "256", "--vocab-size", "300",
        "--grad-accum", "2",  # the r4 combo: accumulation inside the ring
    )
    assert "long-context sp OK" in out
    assert "2 scanned microbatches/update" in out


@pytest.mark.slow
def test_example_long_context_sp_ulysses(tmp_path, sample):
    out = run_example(
        tmp_path, sample, "5_long_context_sp.py",
        "--steps", "6", "--context", "128", "--vocab-size", "300",
        "--ulysses",
    )
    assert "long-context sp OK" in out
    assert "Ulysses all-to-all" in out


@pytest.mark.slow
def test_example_moe_expert_parallel(tmp_path, sample):
    out = run_example(
        tmp_path, sample, "6_moe_expert_parallel.py",
        "--steps", "6", "--vocab-size", "300",
    )
    assert "moe expert-parallel OK" in out


@pytest.mark.slow
def test_example_grad_accum_fsdp(tmp_path, sample):
    out = run_example(tmp_path, sample, "7_grad_accum_fsdp.py")
    assert "matches the single-device full-batch update" in out


def test_example_kv_cache_decode(tmp_path, sample):
    out = run_example(
        tmp_path, sample, "8_kv_cache_decode.py", "--new-tokens", "8"
    )
    assert "decode demo OK" in out
    assert "GQA" in out


@pytest.mark.slow
def test_example_pipeline_parallel(tmp_path, sample):
    out = run_example(tmp_path, sample, "9_pipeline_parallel.py")
    assert "pipeline parallel OK" in out
    assert "matches the single-device update" in out


def test_example_serving(tmp_path, sample):
    out = run_example(tmp_path, sample, "10_serving.py", "--new-tokens", "6")
    assert "serving demo OK" in out
    assert "byte-identical" in out
    assert (tmp_path / "serving_completions.jsonl").exists()


def test_cli_report_on_fixture_jsonl(tmp_path):
    """`bpe-tpu report` smoke: summarize the committed tiny telemetry
    stream (manifest + spans + steps + clean footer) from the CLI."""
    fixture = REPO / "tests" / "fixtures" / "telemetry_tiny.jsonl"
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "bpe_transformer_tpu.training.cli",
            "report",
            str(fixture),
        ],
        capture_output=True,
        text=True,
        cwd=tmp_path,
        # The package may not be pip-installed in the test environment:
        # resolve it from the repo checkout.
        env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=str(REPO)),
        timeout=300,
    )
    assert proc.returncode == 0, f"report failed:\n{proc.stdout}\n{proc.stderr}"
    out = proc.stdout
    assert "== run manifest ==" in out and "mesh={'data': 4}" in out
    assert "steps 10..20" in out
    assert "tokens/sec" in out
    assert "compile_first_step" in out
    assert "anomalies (0)" in out and "clean footer" in out
