"""Paged KV memory: block allocator, radix prefix cache, paged-engine
parity with the dense slot pool, chunked prefill scheduling, and the
kvpool telemetry surface.

The correctness bar (ISSUE 8): the paged engine is **token-identical** to
the dense engine for the same requests/seeds — paging, prefix sharing,
and chunked prefill change memory and scheduling, never tokens.
"""

import dataclasses
import json
import os
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

import jax

from bpe_transformer_tpu.models import TS_TEST_CONFIG, init_params
from bpe_transformer_tpu.serving import Request, ServingEngine
from bpe_transformer_tpu.serving.engine import SlotPoolEngine
from bpe_transformer_tpu.serving.kvpool.blocks import (
    BlockAllocator,
    NoFreeBlocksError,
)
from bpe_transformer_tpu.serving.kvpool.paged_engine import PagedEngine
from bpe_transformer_tpu.serving.kvpool.radix import RadixPrefixCache
from bpe_transformer_tpu.serving.scheduler import PrefillBudget

pytestmark = pytest.mark.serving

REPO = Path(__file__).resolve().parent.parent

CFG = dataclasses.replace(TS_TEST_CONFIG, vocab_size=128, context_length=32)


@pytest.fixture(scope="module")
def setup():
    params = init_params(jax.random.PRNGKey(0), CFG)
    rng = np.random.default_rng(0)
    prompts = [
        [int(t) for t in rng.integers(0, CFG.vocab_size, size=n)]
        for n in (3, 7, 12, 19)
    ]
    return params, prompts


@pytest.fixture(scope="module")
def dense_engine(setup):
    params, _ = setup
    return SlotPoolEngine(params, CFG, slots=2, min_bucket=8)


@pytest.fixture(scope="module")
def paged_engine(setup):
    # Shared across the parity + bounded-compile tests: per-engine jit
    # caches make engines the expensive resource in this module (same
    # policy as test_serving).
    params, _ = setup
    return PagedEngine(params, CFG, slots=2, block_size=8, min_bucket=8)


@pytest.fixture(scope="module")
def chunked_engine(setup):
    params, _ = setup
    return PagedEngine(
        params, CFG, slots=2, block_size=8, min_bucket=8, prefill_chunk=8
    )


def _run(engine, prompt, **knobs):
    event = engine.admit(prompt, **knobs)
    out = [event.token]
    slot = event.slot
    while not event.finished:
        events = engine.tick()
        event = next(e for e in events if e.slot == slot)
        out.append(event.token)
    return out


# ------------------------------------------------------------- allocator


def test_block_allocator_refcounts_and_free_list():
    alloc = BlockAllocator(num_blocks=5, block_size=8)
    assert alloc.usable_blocks == 4 and alloc.free_count == 4
    a = alloc.alloc(2)
    assert 0 not in a, "the trash block must never be handed out"
    alloc.ref([a[0]])  # shared now
    assert alloc.shared_count == 1
    assert alloc.deref([a[0], a[1]]) == 1  # a[1] freed, a[0] still shared->1
    assert alloc.deref([a[0]]) == 1
    assert alloc.free_count == 4 and alloc.shared_count == 0
    with pytest.raises(NoFreeBlocksError):
        alloc.alloc(5)
    assert alloc.free_count == 4, "a failed alloc must not leak blocks"
    with pytest.raises(ValueError):
        alloc.deref([0])


def test_radix_cache_match_insert_evict():
    alloc = BlockAllocator(num_blocks=9, block_size=4)
    cache = RadixPrefixCache(alloc)
    prompt = list(range(11))  # 2 full blocks + a 3-token tail
    blocks = alloc.alloc(3)
    assert cache.insert(prompt, blocks) == 2  # only FULL blocks indexed
    # Matching the same prompt reuses both full blocks (tail stays live).
    matched = cache.match(prompt)
    assert matched == blocks[:2]
    assert alloc.refcount(blocks[0]) == 3  # owner + cache + new match
    # A 9-token prompt sharing one block matches exactly that block —
    # never the whole prompt (the last token must be computed).
    assert cache.match(prompt[:4] + [99, 98, 97, 96, 95]) == blocks[:1]
    # Counters are charged per ADMISSION (engine calls charge), never by
    # match itself — a parked admission's retries must not inflate them.
    assert cache.gauges()["prefix_cache_hits"] == 0
    cache.charge(11, 8)
    cache.charge(9, 4)
    assert cache.gauges()["prefix_cache_hits"] == 8 + 4
    assert cache.gauges()["prefix_cache_misses"] == 3 + 5
    # Release every non-cache reference; eviction then frees LRU leaves.
    alloc.deref(matched)
    alloc.deref(blocks[:1])
    alloc.deref(blocks)
    free_before = alloc.free_count
    assert cache.evict(1) == 1
    assert alloc.free_count == free_before + 1
    # The interior block (prefix of nothing now, but parent of none after
    # the leaf died) becomes evictable next.
    assert cache.evict(5) == 1
    assert len(cache) == 0


def test_prefill_budget_policy():
    budget = PrefillBudget(16)
    budget.start_tick()
    assert budget.admits(64), "the first chunk is always admitted"
    budget.spend(64)
    assert not budget.admits(1)
    budget.start_tick()
    assert budget.admits(8)
    budget.spend(8)
    assert budget.admits(8) and not budget.admits(9)
    assert PrefillBudget(None).admits(10**9)
    with pytest.raises(ValueError):
        PrefillBudget(0)


# ------------------------------------------------------ engine parity


@pytest.mark.slow  # 870s tier-1 budget (PR 11 sweep; ISSUE 11 tooling guard) — runs in the full matrix
def test_paged_parity_with_dense_engine(setup, dense_engine, paged_engine):
    """ACCEPTANCE: the paged engine's outputs are token-identical to the
    dense slot-pool engine for the same requests/seeds — across greedy
    AND seeded temperature/top-k/top-p sampling."""
    params, prompts = setup
    paged = paged_engine
    knobs = [
        dict(temperature=0.0),
        dict(temperature=0.9, top_k=7, top_p=0.8, seed=3),
        dict(temperature=1.0, top_k=2, seed=5),
        dict(temperature=0.7, seed=1),
    ]
    for prompt, kn in zip(prompts, knobs):
        assert _run(paged, prompt, max_new_tokens=8, **kn) == _run(
            dense_engine, prompt, max_new_tokens=8, **kn
        ), f"paged/dense divergence for {kn}"


def test_paged_parity_through_shared_prefix(setup, dense_engine, paged_engine):
    """ACCEPTANCE: radix prefix sharing reuses cached blocks (hits > 0,
    fewer blocks allocated) and the reusing request's outputs stay
    token-identical to the dense engine."""
    params, prompts = setup
    paged = paged_engine
    base = prompts[3]  # 19 tokens: 2 full blocks of 8 + a tail
    first = base + [5, 6]
    second = base + [9, 1, 2]

    assert _run(paged, first, max_new_tokens=6, temperature=0.0) == _run(
        dense_engine, first, max_new_tokens=6, temperature=0.0
    )
    hits_before = paged.gauges()["prefix_cache_hits"]
    slot = paged.begin(second, max_new_tokens=6, temperature=0.0)
    assert paged.slot_shared_len(slot) == 16, "2 full blocks must be reused"
    event = paged.prefill_step(slot)
    while event is None:
        event = paged.prefill_step(slot)
    out = [event.token]
    while not event.finished:
        event = next(e for e in paged.tick() if e.slot == slot)
        out.append(event.token)
    assert out == _run(dense_engine, second, max_new_tokens=6, temperature=0.0)
    assert paged.gauges()["prefix_cache_hits"] == hits_before + 16


def test_paged_parity_with_chunked_prefill(setup, dense_engine, chunked_engine):
    """Chunked prefill (8-token chunks over a 21-token prompt) produces
    the same tokens as the dense whole-prompt prefill."""
    params, prompts = setup
    chunked = chunked_engine
    prompt = prompts[3] + [5, 6]
    for kn in (
        dict(temperature=0.0),
        dict(temperature=0.9, top_k=7, top_p=0.8, seed=3),
    ):
        assert _run(chunked, prompt, max_new_tokens=6, **kn) == _run(
            dense_engine, prompt, max_new_tokens=6, **kn
        )


def test_paged_bounded_compilation_and_block_lifecycle(
    setup, paged_engine, chunked_engine
):
    """ACCEPTANCE: the paged engine compiles at most len(buckets) + 1
    programs over mixed lengths/knobs (the dense engine's contract,
    extended to the paged path), and releases return every block.  Runs
    against the module engines AFTER the parity tests have pushed their
    own mixed lengths/knobs through — the bound covers everything the
    engine has ever served."""
    params, prompts = setup
    engine = paged_engine
    assert engine.buckets == (8, 16, 32)
    for prompt, kn in zip(
        prompts + [prompts[0]],
        [
            dict(temperature=0.0),
            dict(temperature=0.7, top_k=5),
            dict(temperature=1.3, top_p=0.9),
            dict(temperature=0.9, top_k=7, top_p=0.8, seed=3),
            dict(temperature=0.5),
        ],
    ):
        _run(engine, prompt, max_new_tokens=4, **kn)
    assert engine.compiled_programs() <= len(engine.buckets) + 1
    # All slots retired: only prefix-cache references keep blocks busy.
    gauges = engine.gauges()
    held = gauges["kv_blocks_total"] - gauges["kv_blocks_free"]
    assert held == len(engine.prefix_cache)
    # Chunked ladder shrinks the bound, never grows it.
    assert chunked_engine.buckets == (8,)
    _run(chunked_engine, prompts[2], max_new_tokens=2, temperature=0.0)
    assert chunked_engine.compiled_programs() <= len(chunked_engine.buckets) + 1


def test_paged_validation_errors(setup):
    params, _ = setup
    with pytest.raises(ValueError, match="block_size"):
        PagedEngine(params, CFG, block_size=7)
    with pytest.raises(ValueError, match="prefill_chunk"):
        PagedEngine(params, CFG, block_size=8, prefill_chunk=12)
    engine = PagedEngine(params, CFG, slots=1, block_size=8, num_blocks=3)
    # 2 usable blocks = 16 positions: a full-context request can't ever fit.
    with pytest.raises(ValueError, match="KV blocks"):
        engine.begin([1] * 20, max_new_tokens=8)
    with pytest.raises(ValueError, match="no room"):
        engine.begin([1] * 32, max_new_tokens=4)
    with pytest.raises(RuntimeError, match="no free slot"):
        engine.begin([1, 2], max_new_tokens=2)
        engine.begin([1, 2], max_new_tokens=2)


def test_block_starved_pool_raises_then_recovers(setup):
    """A pool too small for two concurrent requests raises
    NoFreeBlocksError for the second; after the first releases, the same
    begin succeeds — the backpressure loop the serving backlog drives."""
    params, prompts = setup
    engine = PagedEngine(
        params, CFG, slots=2, block_size=8, num_blocks=5, prefix_cache=False
    )
    slot = engine.begin(prompts[2], max_new_tokens=20, temperature=0.0)
    with pytest.raises(NoFreeBlocksError):
        engine.begin(prompts[1], max_new_tokens=20)
    engine.release(slot)
    slot2 = engine.begin(prompts[1], max_new_tokens=20, temperature=0.0)
    assert engine.slot_shared_len(slot2) == 0


# ---------------------------------------------------- serving integration


@pytest.mark.slow  # 870s tier-1 budget (PR 11 sweep; ISSUE 11 tooling guard) — runs in the full matrix
def test_block_starved_backlog_parks_expires_and_drains(setup):
    """ServingEngine over a block-starved paged pool, driven by hand: a
    second request parks in the admission backlog; a parked request whose
    deadline lapses fails with "deadline" (the deadline contract follows
    the request out of the scheduler); a deadline-less parked request
    completes once the first retires — no failure, no deadlock."""
    params, prompts = setup
    serving = ServingEngine(
        params, CFG, slots=2, min_bucket=8, paged=True, block_size=8,
        num_kv_blocks=5, prefix_cache=False,
    )
    serving._running = True  # drive the worker loop by hand
    h1 = serving.submit(
        Request(
            prompt_ids=tuple(prompts[2]), max_new_tokens=16,
            temperature=0.0,
        )
    )
    serving._step()  # h1 admits and takes every usable block
    h_dead = serving.submit(
        Request(
            prompt_ids=tuple(prompts[1]), max_new_tokens=16,
            deadline_s=0.01,
        )
    )
    h2 = serving.submit(
        Request(
            prompt_ids=tuple(prompts[1]), max_new_tokens=16,
            temperature=0.0,
        )
    )
    serving._step()  # h_dead popped, block-starved -> parked
    assert serving._admit_backlog, "expected the admission to park"
    time.sleep(0.02)
    serving._step()
    assert h_dead.result(timeout=5).finish_reason == "deadline"
    for _ in range(200):
        serving._step()
        if h1._entry.done.is_set() and h2._entry.done.is_set():
            break
    assert h1.result(timeout=5).finish_reason == "length"
    # The parked survivor was admitted once h1's retirement freed blocks.
    assert h2.result(timeout=5).finish_reason == "length"
    assert len(h2.result().token_ids) >= 1
    serving._running = False
    serving.close()


def test_serving_rejects_request_that_can_never_fit(setup):
    params, prompts = setup
    serving = ServingEngine(
        params, CFG, slots=1, min_bucket=8, paged=True, block_size=8,
        num_kv_blocks=3,
    )
    serving._running = True
    with pytest.raises(ValueError, match="KV blocks"):
        serving.submit(Request(prompt_ids=tuple(range(20)), max_new_tokens=8))


def test_chunked_prefill_interleaves_decode_ticks(setup):
    """ACCEPTANCE (offline, deterministic): under a prefill-token budget,
    a long prompt's chunked prefill interleaves with decode ticks — the
    already-decoding request keeps receiving a token every worker step
    instead of stalling until the whole prefill lands."""
    params, prompts = setup
    serving = ServingEngine(
        params, CFG, slots=2, min_bucket=8, paged=True, block_size=8,
        prefill_chunk=8, prefill_token_budget=8,
    )
    serving._running = True  # drive the worker loop by hand
    h1 = serving.submit(
        Request(prompt_ids=(1, 2, 3), max_new_tokens=24, temperature=0.0)
    )
    serving._step()  # admit + one-chunk prefill + first tick
    assert serving.engine.active_count == 1

    # 24-token prompt -> 3 chunks of 8 under the budget: 3 worker steps.
    serving.submit(
        Request(
            prompt_ids=tuple(int(t) for t in prompts[3]) + (1, 2, 3, 4, 5),
            max_new_tokens=2, temperature=0.0,
        )
    )
    ticks_before = serving.engine.ticks
    tokens_before = len(serving._slot_entries[h1._entry.slot].tokens)
    serving._step()  # admits the long prompt + runs chunk 1 of 3 + a tick
    assert serving._prefill_entries, "prefill must span multiple steps"
    steps = 1
    while serving._prefill_entries and steps < 10:
        serving._step()
        steps += 1
    assert steps == 3, f"expected 3 budgeted chunk steps, took {steps}"
    # EVERY one of those steps also ran a decode tick: no starvation.
    assert serving.engine.ticks == ticks_before + 3
    assert (
        len(serving._slot_entries[h1._entry.slot].tokens)
        == tokens_before + 3
    )
    # Drain the rest so close() isn't cancelling live work.
    while serving._slot_entries or serving._prefill_entries:
        serving._step()
    serving._running = False
    serving.close()


def test_serving_paged_telemetry_kvpool_records(setup):
    """A paged serving run emits schema-valid kind="kvpool" records and
    the kv gauges reach stats()/statusz()/Prometheus."""
    from bpe_transformer_tpu.telemetry import Telemetry, validate_record
    from bpe_transformer_tpu.telemetry.monitor import parse_prometheus

    params, prompts = setup
    records = []
    telemetry = Telemetry(sink=records.append)
    with ServingEngine(
        params, CFG, slots=2, min_bucket=8, paged=True, block_size=8,
        telemetry=telemetry, engine_record_every_s=0.0,
    ) as serving:
        base = prompts[3]
        # Serialized on purpose: the second request must arrive AFTER the
        # first's prefill has indexed its blocks (two racing identical
        # prefills legitimately miss the dedup — documented behavior).
        serving.generate(base + [5], max_new_tokens=4, temperature=0.0)
        serving.generate(base + [9, 1], max_new_tokens=4, temperature=0.0)
        stats = serving.stats()
        page = serving.statusz()
        prom = parse_prometheus(serving.prometheus_metrics())

    kvpool = [r for r in records if r.get("kind") == "kvpool"]
    assert kvpool, "paged run emitted no kvpool records"
    for record in kvpool:
        assert validate_record(record) == []
    assert kvpool[-1]["prefix_hits"] > 0
    assert kvpool[-1]["blocks_total"] == stats["kv_blocks_total"]

    assert stats["engine_kind"] == "paged"
    assert stats["prefix_cache_hits"] == 16
    assert stats["kv_blocks_free"] > 0
    assert page["kvpool"]["kv_blocks_total"] == stats["kv_blocks_total"]
    assert page["engine_kind"] == "paged"
    assert page["draining"] is False
    json.dumps(page)

    assert prom["bpe_tpu_kv_blocks_total"] == stats["kv_blocks_total"]
    assert prom["bpe_tpu_prefix_cache_hits_total"] == 16
    assert prom["bpe_tpu_kv_blocks_free"] == stats["kv_blocks_free"]
    assert "bpe_tpu_prefill_pending_tokens" in prom


def test_kvpool_fixture_pins_report_and_compare_gate():
    """The committed kvpool fixture renders the report's kv-pool section
    and feeds the prefix_hit_rate / kv_blocks_free compare-gate metrics."""
    from bpe_transformer_tpu.telemetry.report import (
        extract_compare_metrics,
        load_records,
        render_report,
        summarize,
    )

    records = load_records(REPO / "tests" / "fixtures" / "kvpool_tiny.jsonl")
    report = render_report(records)
    assert "== kv pool (3 samples) ==" in report
    assert "hit rate 60.0%" in report
    assert "free last 52 (min 31)" in report
    assert "chunked-prefill backlog max 128" in report
    assert "pool 1.1 MiB  kv/token 384 B" in report

    metrics = extract_compare_metrics(summarize(records))
    assert metrics["prefix_hit_rate"] == (0.6, "higher")
    assert metrics["kv_blocks_free"] == (31.0, "higher")
    # KV-memory gate rows (ISSUE 9): pinned so `report --baseline` can
    # flag a run that lost the int8 win.
    assert metrics["kv_bytes_per_token"] == (384.0, "lower")
    assert metrics["kv_pool_bytes"] == (1179648.0, "lower")


def test_monitor_folds_kvpool_records():
    from bpe_transformer_tpu.telemetry.monitor import (
        fold_records,
        render_frame,
    )

    state = fold_records(
        [
            {"kind": "manifest", "run_kind": "serve", "time_utc": "x",
             "host": "h"},
            {"kind": "kvpool", "t": 1.0, "blocks_total": 64,
             "blocks_free": 31, "blocks_shared": 6, "prefix_hits": 96,
             "prefix_misses": 128, "prefix_hit_rate": 0.428571,
             "prefill_pending_tokens": 40},
        ]
    )
    assert state["kv_blocks_free"] == 31
    frame = render_frame(state, "test")
    assert "blocks 31/64 free" in frame
    assert "prefix hit 43%" in frame
    assert "prefill backlog 40" in frame


# ----------------------------------------------------------- warmup CLI


@pytest.mark.slow
def test_warmup_cli_two_process_cache_hits(tmp_path):
    """ACCEPTANCE (ROADMAP item 5 stub): `bpe-tpu warmup` AOT-compiles
    the serving ladder into the persistent compile cache; a second
    process (the restarted replica) is served from disk — its cache-hit
    counter climbs while the cold one's stays 0."""
    cache_dir = tmp_path / "xla_cache"

    def run():
        proc = subprocess.run(
            [
                sys.executable, "-m", "bpe_transformer_tpu.training.cli",
                "warmup", "--compile-cache", str(cache_dir),
                "--preset", "ts-test", "--paged", "--block-size", "8",
                "--slots", "2", "--decode-attention", "paged",
                "--weight-dtype", "both", "--fused-sampling",
            ],
            capture_output=True, text=True, timeout=900,
            env={**os.environ, "JAX_PLATFORMS": "cpu", "XLA_FLAGS": "",
                 "PYTHONPATH": str(REPO)},
            cwd=str(REPO),
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        return json.loads(proc.stdout.strip().splitlines()[-1])

    cold = run()
    assert cold["cache_hits"] == 0
    # Default --kv-dtype both x --weight-dtype both: all four pool-width x
    # weight-width ladders are warmed (ISSUE 9 + ISSUE 11), ONE engine
    # resident at a time, each within the per-engine bounded-compile
    # contract — a replica restarting with any knob combination hits.
    assert cold["kv_dtypes"] == ["act", "int8"]
    assert cold["weight_dtypes"] == ["act", "int8"]
    assert cold["fused_sampling"] is True
    assert cold["decode_attention"] == "paged"
    # 4 ladders (kv x weight widths, buckets + tick each) + the both-role
    # migration pair (inject + extract), warmed once per POOL width —
    # weight width doesn't change the migration programs (ISSUE 15).
    assert cold["programs_compiled"] <= 4 * (len(cold["buckets"]) + 1) + 4
    assert any(cache_dir.rglob("*")), "warmup wrote no cache entries"
    warm = run()
    assert warm["cache_hits"] > 0


# ----------------------------------- paged-native kernel + int8 KV blocks


CFG_NATIVE = dataclasses.replace(CFG, decode_attention_impl="paged")


@pytest.fixture(scope="module")
def native_engine(setup):
    """Paged engine on the block-pool-NATIVE flash-decode kernel: the tick
    reads K/V straight out of the pool through the kernel's index maps."""
    params, _ = setup
    return PagedEngine(params, CFG_NATIVE, slots=2, block_size=8, min_bucket=8)


@pytest.fixture(scope="module")
def int8_engine(setup):
    params, _ = setup
    return PagedEngine(
        params, CFG_NATIVE, slots=2, block_size=8, min_bucket=8,
        kv_dtype="int8",
    )


def test_paged_native_parity_with_dense_engine(setup, dense_engine, native_engine):
    """ACCEPTANCE (ISSUE 9): the paged-NATIVE tick is token-identical to
    the dense engine across greedy AND seeded temperature/top-k/top-p
    sampling — deleting the gather transient changes bytes moved, never
    tokens."""
    params, prompts = setup
    knobs = [
        dict(temperature=0.0),
        dict(temperature=0.9, top_k=7, top_p=0.8, seed=3),
        dict(temperature=1.0, top_k=2, seed=5),
        dict(temperature=0.7, seed=1),
    ]
    for prompt, kn in zip(prompts, knobs):
        assert _run(native_engine, prompt, max_new_tokens=8, **kn) == _run(
            dense_engine, prompt, max_new_tokens=8, **kn
        ), f"paged-native/dense divergence for {kn}"


def test_paged_native_parity_through_shared_prefix(
    setup, dense_engine, native_engine
):
    """Radix-shared blocks read through the kernel's index maps stay
    token-identical to the dense engine."""
    params, prompts = setup
    base = prompts[3]
    first = base + [15, 16]
    second = base + [19, 11, 12]
    assert _run(native_engine, first, max_new_tokens=6, temperature=0.0) == \
        _run(dense_engine, first, max_new_tokens=6, temperature=0.0)
    slot = native_engine.begin(second, max_new_tokens=6, temperature=0.0)
    assert native_engine.slot_shared_len(slot) == 16
    event = native_engine.prefill_step(slot)
    while event is None:
        event = native_engine.prefill_step(slot)
    out = [event.token]
    while not event.finished:
        event = next(e for e in native_engine.tick() if e.slot == slot)
        out.append(event.token)
    assert out == _run(dense_engine, second, max_new_tokens=6,
                       temperature=0.0)


def test_paged_native_parity_with_chunked_prefill(setup, dense_engine):
    """Chunked prefill feeding the paged-native tick: same tokens as the
    dense whole-prompt engine."""
    params, prompts = setup
    chunked = PagedEngine(
        params, CFG_NATIVE, slots=2, block_size=8, min_bucket=8,
        prefill_chunk=8,
    )
    prompt = prompts[3] + [5, 6]
    for kn in (
        dict(temperature=0.0),
        dict(temperature=0.9, top_k=7, top_p=0.8, seed=3),
    ):
        assert _run(chunked, prompt, max_new_tokens=6, **kn) == _run(
            dense_engine, prompt, max_new_tokens=6, **kn
        )
    assert chunked.compiled_programs() <= len(chunked.buckets) + 1


def test_paged_native_bounded_compilation(native_engine, int8_engine):
    """ACCEPTANCE: the paged-native ladder keeps the dense engine's
    compile contract — tables/pos ride the tick's traced args, so every
    occupancy pattern shares one tick program (runs AFTER the parity
    tests have pushed mixed lengths/knobs through the module engines)."""
    assert native_engine.compiled_programs() <= len(native_engine.buckets) + 1
    assert int8_engine.compiled_programs() <= len(int8_engine.buckets) + 1


def test_paged_native_tick_contains_no_gather_transient(setup):
    """ACCEPTANCE (ISSUE 9 tentpole): the compiled paged-native tick holds
    NO ``(slots, blocks_per_slot, kv_heads, block_size, d_head)``
    contiguous KV gather — the transient `gather_paged_kv` materializes
    per layer per tick is structurally absent from the HLO, while the
    gather-path tick provably contains it.  On a real TPU the XLA
    cost-model bytes-accessed of the native tick must also undercut the
    gather path's; the CPU interpreter is excluded from that bound
    because it lowers the kernel's VMEM scratch to counted host buffers
    (scratch traffic is on-chip on hardware)."""
    import functools

    import jax

    from bpe_transformer_tpu.models.decode import init_kv_pool
    from bpe_transformer_tpu.models.transformer import lm_head_weight
    from bpe_transformer_tpu.serving.engine import (
        TOP_K_DISABLED,
        TOP_P_DISABLED,
    )
    from bpe_transformer_tpu.serving.kvpool.paged_engine import (
        _paged_tick_program,
    )
    from bpe_transformer_tpu.telemetry.attribution import program_cost

    params, _ = setup
    slots, bs = 2, 8
    nbs = CFG.context_length // bs
    kv_heads = CFG.num_kv_heads or CFG.num_heads
    pool = init_kv_pool(CFG, slots * nbs + 1, bs)
    tables = np.arange(1, slots * nbs + 1, dtype=np.int32).reshape(slots, nbs)
    argvals = (
        params, lm_head_weight(params, CFG), pool, tables,
        np.zeros(slots, np.int32), np.full(slots, 12, np.int32),
        np.ones(slots, bool), np.zeros((slots, 2), np.uint32),
        np.zeros(slots, np.float32),
        np.full(slots, TOP_K_DISABLED, np.int32),
        np.full(slots, TOP_P_DISABLED, np.float32),
    )
    transient = "{},{},{},{},{}".format(
        slots, nbs, kv_heads, bs, CFG.d_head
    )
    compiled = {}
    for name, cfg in (("gather", CFG), ("native", CFG_NATIVE)):
        fn = jax.jit(
            functools.partial(_paged_tick_program, config=cfg, block_size=bs)
        )
        compiled[name] = fn.lower(*argvals).compile()
    hlo = {
        name: prog.as_text().replace(" ", "")
        for name, prog in compiled.items()
    }
    assert transient in hlo["gather"], (
        "sanity: the gather path must materialize the contiguous transient"
    )
    assert transient not in hlo["native"], (
        "the paged-native tick still materializes the gathered KV transient"
    )
    if jax.default_backend() != "cpu":
        bytes_native = program_cost(compiled["native"])["bytes_accessed"]
        bytes_gather = program_cost(compiled["gather"])["bytes_accessed"]
        if bytes_native and bytes_gather:
            assert bytes_native < bytes_gather, (
                f"paged-native tick moves {bytes_native:.0f} bytes vs the "
                f"gather path's {bytes_gather:.0f}"
            )


def test_int8_pool_bytes_and_per_token_footprint(setup):
    """ACCEPTANCE: at FIXED block count, the int8 pool (scale pools
    included) halves the bf16 pool's resident bytes and quarters f32's;
    kv_bytes_per_token is exactly 2x/4x smaller."""
    params, _ = setup
    kwargs = dict(slots=2, block_size=8, min_bucket=8, prefix_cache=False)
    f32 = PagedEngine(params, CFG, **kwargs)
    i8 = PagedEngine(params, CFG, kv_dtype="int8", **kwargs)
    bf16_cfg = dataclasses.replace(CFG, activation_dtype="bfloat16")
    bf16 = PagedEngine(params, bf16_cfg, **kwargs)
    assert f32.allocator.num_blocks == i8.allocator.num_blocks

    assert i8.kv_bytes_per_token * 4 == f32.kv_bytes_per_token
    assert i8.kv_bytes_per_token * 2 == bf16.kv_bytes_per_token
    # Pool bytes: int8 payload is exactly 1/4 (1/2) of f32 (bf16); the f32
    # scale pools add 2 * 4 bytes per (block, kv_head) on top.
    assert i8.kv_pool_bytes < 0.27 * f32.kv_pool_bytes
    assert i8.kv_pool_bytes < 0.53 * bf16.kv_pool_bytes
    gauges = i8.gauges()
    assert gauges["kv_pool_bytes"] == i8.kv_pool_bytes
    assert gauges["kv_bytes_per_token"] == i8.kv_bytes_per_token
    assert i8.kv_dtype == "int8" and f32.kv_dtype == "float32"


def test_int8_logit_error_bound(setup):
    """ACCEPTANCE: teacher-forced decode over the int8 pool stays within a
    documented logit max-abs-error bound of the full-width pool — the
    quantization contract the long-decode smoke rides on.  (Measured
    ~2e-3 at this config's ~0.5 logit scale; the bound leaves 20x
    headroom.)"""
    from bpe_transformer_tpu.models.decode import (
        init_kv_pool,
        paged_chunk_prefill,
        paged_decode_step,
    )

    params, prompts = setup
    import jax.numpy as jnp

    bs, nbs = 8, 4
    prompt = prompts[2]  # 12 tokens
    tables = jnp.asarray([[1, 2, 3, 4], [5, 6, 7, 8]], jnp.int32)
    chunk = jnp.asarray([prompt + [0] * (16 - len(prompt))], jnp.int32)

    def drive(kv_dtype):
        pool = init_kv_pool(CFG, 9, bs, kv_dtype=kv_dtype)
        logits, pool = paged_chunk_prefill(
            params, chunk, jnp.int32(0), jnp.int32(len(prompt)), tables[0],
            pool, CFG, block_size=bs,
        )
        rows = [logits]
        tok = int(jnp.argmax(logits[0]))
        pos = jnp.asarray([len(prompt), 0], jnp.int32)
        active = jnp.asarray([True, False])
        for _ in range(8):
            logits, pool = paged_decode_step(
                params, jnp.asarray([tok, 0], jnp.int32), pos, pool, tables,
                CFG, active=active, block_size=bs,
            )
            rows.append(logits[0:1])
            tok = int(jnp.argmax(logits[0]))  # teacher = fp32 path's argmax
            pos = pos + jnp.asarray([1, 0], jnp.int32)
        return jnp.concatenate(rows, axis=0)

    fp = drive(None)
    i8 = drive("int8")
    err = float(jnp.max(jnp.abs(fp - i8)))
    assert err < 0.05, f"int8 KV logit error {err} exceeds the 0.05 bound"


@pytest.mark.slow  # 870s tier-1 budget (PR 11 sweep; ISSUE 11 tooling guard) — runs in the full matrix
def test_int8_long_decode_quality_smoke(setup, dense_engine, int8_engine):
    """Long-decode smoke vs the full-width pool: a 16-token greedy decode
    through the int8 engine (paged-native kernel) overwhelmingly agrees
    with the dense fp32 engine, shared-prefix reuse included."""
    params, prompts = setup
    out = _run(int8_engine, prompts[2], max_new_tokens=16, temperature=0.0)
    ref = _run(dense_engine, prompts[2], max_new_tokens=16, temperature=0.0)
    assert len(out) == len(ref) == 16
    assert all(0 <= t < CFG.vocab_size for t in out)
    agree = sum(a == b for a, b in zip(out, ref))
    assert agree >= 12, f"int8 decode agreed on only {agree}/16 tokens"
    # Shared-prefix reuse of QUANTIZED frozen blocks stays coherent.
    base = prompts[3]
    first = _run(int8_engine, base + [21], max_new_tokens=4, temperature=0.0)
    slot = int8_engine.begin(base + [22], max_new_tokens=4, temperature=0.0)
    assert int8_engine.slot_shared_len(slot) == 16
    event = int8_engine.prefill_step(slot)
    while event is None:
        event = int8_engine.prefill_step(slot)
    out2 = [event.token]
    while not event.finished:
        event = next(e for e in int8_engine.tick() if e.slot == slot)
        out2.append(event.token)
    unshared = _run(
        int8_engine, base + [22], max_new_tokens=4, temperature=0.0
    )
    assert out2 == unshared, "shared int8 blocks changed the tokens"


def test_serving_int8_stats_telemetry_and_prometheus(setup):
    """ServingEngine wiring: kv_dtype reaches the engine, and the
    kv_pool_bytes / kv_bytes_per_token gauges surface in stats(),
    /statusz, Prometheus, and schema-valid kvpool records."""
    from bpe_transformer_tpu.telemetry import Telemetry, validate_record
    from bpe_transformer_tpu.telemetry.monitor import parse_prometheus

    params, prompts = setup
    records = []
    telemetry = Telemetry(sink=records.append)
    with ServingEngine(
        params, CFG_NATIVE, slots=2, min_bucket=8, paged=True, block_size=8,
        kv_dtype="int8", telemetry=telemetry, engine_record_every_s=0.0,
    ) as serving:
        serving.generate(prompts[1], max_new_tokens=4, temperature=0.0)
        stats = serving.stats()
        page = serving.statusz()
        prom = parse_prometheus(serving.prometheus_metrics())

    assert stats["kv_dtype"] == "int8"
    assert stats["kv_pool_bytes"] > 0
    assert stats["kv_bytes_per_token"] > 0
    assert page["kvpool"]["kv_dtype"] == "int8"
    assert page["kvpool"]["kv_pool_bytes"] == stats["kv_pool_bytes"]
    assert prom["bpe_tpu_kv_pool_bytes"] == stats["kv_pool_bytes"]
    assert prom["bpe_tpu_kv_bytes_per_token"] == stats["kv_bytes_per_token"]

    kvpool = [r for r in records if r.get("kind") == "kvpool"]
    assert kvpool, "no kvpool records emitted"
    for record in kvpool:
        assert validate_record(record) == []
    assert kvpool[-1]["kv_pool_bytes"] == stats["kv_pool_bytes"]
    assert kvpool[-1]["kv_bytes_per_token"] == stats["kv_bytes_per_token"]


def test_cli_serve_flag_validation():
    """--kv-dtype int8 / --decode-attention paged are paged-engine knobs:
    `bpe-tpu serve` fails fast (rc 2) when --paged is missing, before any
    jax/checkpoint work."""
    import argparse

    from bpe_transformer_tpu.training.cli import cmd_serve

    base = dict(prompts_file=None, output=None, compile_cache=None,
                paged=False, speculate=0, draft_config=None, role="both",
                evacuate_to=None)
    args = argparse.Namespace(kv_dtype="int8", decode_attention=None, **base)
    assert cmd_serve(args) == 2
    args = argparse.Namespace(kv_dtype="act", decode_attention="paged",
                              **base)
    assert cmd_serve(args) == 2
    # Disaggregated roles are paged-engine knobs too (ISSUE 15).
    args = argparse.Namespace(
        kv_dtype="act", decode_attention=None,
        **{**base, "role": "prefill"},
    )
    assert cmd_serve(args) == 2
    # Drain evacuation ships KV block chains: --evacuate-to needs --paged.
    args = argparse.Namespace(
        kv_dtype="act", decode_attention=None,
        **{**base, "evacuate_to": ["http://peer:8001"]},
    )
    assert cmd_serve(args) == 2


# --------------------------------------------- KV rewind primitive (ISSUE 10)


def _drive_to_decode(engine, prompt, **knobs):
    """begin + run all prefill chunks; returns the ACTIVE slot (the test
    owns ticks/rewinds from here)."""
    slot = engine.begin(prompt, **knobs)
    event = engine.prefill_step(slot)
    while event is None:
        event = engine.prefill_step(slot)
    assert not event.finished
    return slot


def test_rewind_within_block_is_bookkeeping(setup):
    """Frontier rollback inside a block releases nothing and copies
    nothing: abandoned rows stay in the pool, invisible behind the
    position mask."""
    params, prompts = setup
    engine = PagedEngine(
        params, CFG, slots=1, block_size=8, min_bucket=8, prefix_cache=False
    )
    slot = _drive_to_decode(engine, prompts[2], max_new_tokens=4,
                            temperature=0.0)
    blocks_before = list(engine._slots[slot].block_ids)
    free_before = engine.allocator.free_count
    result = engine.rewind(slot, 13)
    assert result == {"released": 0, "cow": False}
    assert engine._slots[slot].block_ids == blocks_before
    assert engine.allocator.free_count == free_before
    engine.release(slot)
    assert engine.allocator.free_count == engine.allocator.usable_blocks


def test_rewind_across_block_boundary_releases_blocks(setup):
    """ACCEPTANCE (satellite): blocks wholly beyond the rewound frontier
    return to the pool — except below the ``keep_blocks`` floor, which
    pins the admission reservation mid-flight."""
    params, prompts = setup
    engine = PagedEngine(
        params, CFG, slots=1, block_size=8, min_bucket=8, prefix_cache=False
    )
    # 12-token prompt + 12 new = 24 positions = 3 blocks reserved.
    slot = _drive_to_decode(engine, prompts[2], max_new_tokens=12,
                            temperature=0.0)
    assert len(engine._slots[slot].block_ids) == 3
    # Speculative scratch: grow to the full context (4 blocks).
    engine.extend_blocks(slot, 32)
    assert len(engine._slots[slot].block_ids) == 4
    free_before = engine.allocator.free_count
    # keep_blocks floors at the reservation: only the scratch comes back.
    result = engine.rewind(slot, 13, keep_blocks=3)
    assert result["released"] == 1 and not result["cow"]
    assert engine.allocator.free_count == free_before + 1
    assert len(engine._slots[slot].block_ids) == 3
    assert list(engine._tables[slot][3:]) == [0]
    # Without the floor the frontier math rules: 13 tokens need 2 blocks.
    result = engine.rewind(slot, 13)
    assert result["released"] == 1
    assert len(engine._slots[slot].block_ids) == 2
    # Rewinding further than the floor allows is a no-op on the chain.
    result = engine.rewind(slot, 2, keep_blocks=2)
    assert result["released"] == 0
    assert len(engine._slots[slot].block_ids) == 2
    engine.release(slot)
    assert engine.allocator.free_count == engine.allocator.usable_blocks


def test_rewind_validation_errors(setup):
    params, prompts = setup
    engine = PagedEngine(
        params, CFG, slots=1, block_size=8, min_bucket=8, prefill_chunk=8,
        prefix_cache=False,
    )
    with pytest.raises(ValueError, match="not occupied"):
        engine.rewind(0, 4)
    with pytest.raises(ValueError, match="not occupied"):
        engine.extend_blocks(0, 16)
    slot = engine.begin(prompts[3], max_new_tokens=4, temperature=0.0)
    assert engine.prefill_step(slot) is None  # still mid-prefill
    with pytest.raises(ValueError, match="mid-prefill"):
        engine.rewind(slot, 4)
    event = engine.prefill_step(slot)
    while event is None:
        event = engine.prefill_step(slot)
    with pytest.raises(ValueError, match="outside"):
        engine.rewind(slot, -1)
    with pytest.raises(ValueError, match="outside"):
        engine.rewind(slot, CFG.context_length + 1)
    engine.release(slot)


def test_rewind_into_radix_shared_block_copies_on_write(setup):
    """ACCEPTANCE (satellite): rewinding the frontier into a radix-shared
    block replaces it with a fresh device copy — the cache's copy is
    never mutated, other chains keep reading the original bytes, and the
    copy is bit-identical at copy time."""
    params, prompts = setup
    engine = PagedEngine(
        params, CFG, slots=2, block_size=8, min_bucket=8
    )
    prompt = prompts[3][:16]  # 2 full blocks
    # First generation indexes the prompt's full blocks into the cache.
    ref = _run(engine, prompt, max_new_tokens=2, temperature=0.0)
    # Re-admit: the first block arrives radix-shared (match cap plen-1).
    slot = _drive_to_decode(engine, prompt, max_new_tokens=2,
                            temperature=0.0)
    info = engine._slots[slot]
    assert info.shared_len == 8
    shared = info.block_ids[0]
    rc_before = engine.allocator.refcount(shared)
    assert rc_before >= 2  # cache + this slot
    old_rows = {
        layer_idx: np.asarray(layer["k"])[shared].copy()
        for layer_idx, layer in enumerate(engine._pool)
    }
    result = engine.rewind(slot, 4)
    assert result["cow"] and result["released"] >= 1
    fresh = info.block_ids[0]
    assert fresh != shared
    assert engine._tables[slot][0] == fresh
    # The shared copy lost exactly this slot's reference; the cache still
    # serves it, bytes untouched.
    assert engine.allocator.refcount(shared) == rc_before - 1
    assert engine.prefix_cache.match([int(t) for t in prompt]) == [shared]
    engine.allocator.deref([shared])  # drop the match's reference
    for layer_idx, layer in enumerate(engine._pool):
        np.testing.assert_array_equal(
            np.asarray(layer["k"])[fresh], old_rows[layer_idx]
        )
        np.testing.assert_array_equal(
            np.asarray(layer["k"])[shared], old_rows[layer_idx]
        )
    # CoW costs exactly one extra compiled program, once.
    assert engine._copy_jit._cache_size() == 1
    engine.release(slot)
    # A later identical prompt still hits the (unmutated) cached prefix.
    assert _run(engine, prompt, max_new_tokens=2, temperature=0.0) == ref


@pytest.mark.slow  # 870s tier-1 budget (PR 11 sweep; ISSUE 11 tooling guard) — runs in the full matrix
def test_rewind_then_regrow_int8_scales_coherent(setup):
    """ACCEPTANCE (satellite): int8 block scales stay sound across rewind
    -> regrow.  Within one occupancy the scale is monotone (rewound rows'
    magnitude stays folded in — documented, not repaired); a released
    block re-acquired and written at offset 0 RESETS its base scale, so
    recycled-block leftovers never leak."""
    params, prompts = setup
    engine = PagedEngine(
        params, CFG, slots=1, block_size=8, min_bucket=8,
        prefix_cache=False, kv_dtype="int8",
    )
    prompt = prompts[0]  # 3 tokens
    slot = _drive_to_decode(engine, prompt, max_new_tokens=24,
                            temperature=0.0)
    # Decode across the first block boundary: positions 3..11.
    for _ in range(9):
        engine.tick()
    assert int(engine._positions[slot]) == 12
    b1 = engine._slots[slot].block_ids[1]  # holds positions 8..11
    scale_before = np.asarray(engine._pool[0]["k_scale"])[b1].copy()
    assert (scale_before > 0).all()
    # Mid-block rewind (stale rows 10..11), then regrow: the engine's
    # decode cursor is host state, so emulate the spec engine's usage —
    # roll KV back and the cursor with it.
    engine.rewind(slot, 10, keep_blocks=2)
    engine._positions[slot] = 10
    for _ in range(4):
        engine.tick()
    scale_after = np.asarray(engine._pool[0]["k_scale"])[b1]
    assert np.isfinite(scale_after).all()
    assert (scale_after >= scale_before - 1e-7).all(), (
        "block scale shrank mid-occupancy: rewound rows' magnitude must "
        "stay folded into the scale until the block is vacated"
    )
    # Cross-boundary rewind: release block b1 entirely, then regrow into
    # a recycled block — offset-0 write resets the base scale (no leak
    # from the previous occupancy).
    engine.rewind(slot, 8, keep_blocks=1)
    assert len(engine._slots[slot].block_ids) == 1
    engine._positions[slot] = 8
    engine.extend_blocks(slot, 16)
    b1_new = engine._slots[slot].block_ids[1]
    engine.tick()  # writes position 8 = offset 0 of the regrown block
    fresh_scale = np.asarray(engine._pool[0]["k_scale"])[b1_new]
    row = np.asarray(engine._pool[0]["k"])[b1_new][:, 0, :]
    assert (fresh_scale > 0).all()
    # Reset semantics: the fresh base scale fits exactly one row — the
    # quantized row must hit the int8 rail (127) for the max head.
    assert np.abs(row).max() == 127, (
        "offset-0 regrow did not reset the block scale to the new row"
    )
    out_tokens = []
    while len(out_tokens) < 4:
        for e in engine.tick():
            out_tokens.append(e.token)
    assert all(0 <= t < CFG.vocab_size for t in out_tokens)
    engine.release(slot)


@pytest.mark.slow  # 870s tier-1 budget (PR 11 sweep; ISSUE 11 tooling guard) — runs in the full matrix
def test_allocator_no_leak_under_rewind_churn(setup):
    """ACCEPTANCE (satellite): randomized admit / extend / rewind /
    release churn returns every block — the allocator's free count ends
    where it started and nothing stays shared."""
    params, prompts = setup
    engine = PagedEngine(
        params, CFG, slots=2, block_size=8, min_bucket=8, prefix_cache=False
    )
    usable = engine.allocator.usable_blocks
    rng = np.random.default_rng(7)
    for round_idx in range(12):
        prompt = prompts[int(rng.integers(0, len(prompts)))]
        new = int(rng.integers(1, 10))
        try:
            slot = _drive_to_decode(
                engine, prompt, max_new_tokens=new, temperature=0.0
            )
        except NoFreeBlocksError:
            continue
        keep = engine.blocks_needed(len(prompt), new)
        for _ in range(int(rng.integers(0, 3))):
            try:
                engine.extend_blocks(
                    slot, int(engine._positions[slot]) + int(
                        rng.integers(1, 8)
                    )
                )
            except NoFreeBlocksError:
                pass
            engine.tick()
            if engine._slots[slot] is None:
                break  # the tick finished the request (auto-released)
            engine.rewind(
                slot, int(engine._positions[slot]), keep_blocks=keep
            )
        if engine._slots[slot] is not None:
            engine.release(slot)
    assert engine.allocator.free_count == usable
    assert engine.allocator.shared_count == 0


# --------------------------------------- KV migration (ISSUE 15 tentpole)


from bpe_transformer_tpu.serving.kvpool.migrate import (  # noqa: E402
    payload_from_bytes,
    payload_nbytes,
    payload_to_bytes,
    synthetic_decode_payload,
)


@pytest.fixture(scope="module")
def migration_target(setup):
    """A second engine, same geometry — the 'replica B' every migration
    test grafts into (module-scoped: engines are the expensive resource)."""
    params, _ = setup
    return PagedEngine(params, CFG, slots=2, block_size=8, min_bucket=8)


def _continue_on(engine, slot, event):
    out = []
    while not event.finished:
        event = next(e for e in engine.tick() if e.slot == slot)
        out.append(event.token)
    return out


def test_payload_codec_roundtrip_and_corruption():
    """The wire format is self-describing and fails loudly: bytes round
    trip exactly; bad magic, wrong version, and truncation raise."""
    payload = synthetic_decode_payload(
        CFG, block_size=8, kv_dtype="int8", prompt_len=9, max_new_tokens=3
    )
    data = payload_to_bytes(payload)
    back = payload_from_bytes(data)
    assert back["meta"] == payload["meta"]
    for a, b in zip(payload["layers"], back["layers"]):
        for name in a:
            np.testing.assert_array_equal(a[name], b[name])
    assert payload_nbytes(back) == payload_nbytes(payload)
    with pytest.raises(ValueError, match="magic"):
        payload_from_bytes(b"nonsense")
    with pytest.raises(ValueError, match="version"):
        payload_from_bytes(b"BPEKV999" + data[8:])
    with pytest.raises(ValueError, match="truncated"):
        payload_from_bytes(data[: len(data) - 64])


def test_payload_wire_v2_compression_and_crc():
    """ISSUE 20 wire hardening: every advertised codec round trips the
    frame exactly; a single bit flipped in the array section is caught by
    the CRC (the corruption no structural check can see); a corrupted
    compressed body fails loudly instead of grafting garbage."""
    from bpe_transformer_tpu.serving.kvpool.migrate import (
        HAVE_ZSTD,
        supported_codecs,
    )

    payload = synthetic_decode_payload(
        CFG, block_size=8, kv_dtype="int8", prompt_len=9, max_new_tokens=3
    )
    codecs = supported_codecs()
    assert codecs[-1] == "raw" and "zlib" in codecs
    assert ("zstd" in codecs) == HAVE_ZSTD
    for codec in codecs:
        data = payload_to_bytes(payload, codec=codec)
        assert data.startswith(b"BPEKV002")
        back = payload_from_bytes(data)
        assert back["meta"] == payload["meta"]
        for a, b in zip(payload["layers"], back["layers"]):
            for name in a:
                np.testing.assert_array_equal(a[name], b[name])

    # Bit flip in the raw array section: only the CRC can catch it.
    raw = payload_to_bytes(payload, codec="raw")
    buf = bytearray(raw)
    buf[(len(buf) * 3) // 4] ^= 0xFF
    with pytest.raises(ValueError, match="CRC"):
        payload_from_bytes(bytes(buf))

    # Bit flip inside a COMPRESSED body: either the codec or the CRC
    # must refuse it — never a silent graft.
    z = payload_to_bytes(payload, codec="zlib")
    zbuf = bytearray(z)
    zbuf[len(zbuf) - 8] ^= 0xFF
    with pytest.raises(ValueError, match="corrupt|CRC"):
        payload_from_bytes(bytes(zbuf))
    with pytest.raises(ValueError, match="truncated"):
        payload_from_bytes(z[: len(z) - 4])
    with pytest.raises(ValueError, match="codec"):
        payload_to_bytes(payload, codec="lz9")


def test_payload_codec_negotiation_and_legacy_v1():
    """Codec negotiation picks the best locally available codec from the
    peer's accept list and degrades to raw for pre-negotiation peers;
    legacy BPEKV001 frames (PR 14, no CRC/compression) still decode."""
    import json as _json

    from bpe_transformer_tpu.serving.kvpool.migrate import (
        HAVE_ZSTD,
        PAYLOAD_MAGIC,
        PAYLOAD_MAGIC_V1,
        negotiate_codec,
    )

    assert negotiate_codec(None) == "raw"
    assert negotiate_codec("") == "raw"
    assert negotiate_codec("bogus,codecs") == "raw"
    assert negotiate_codec("zlib , raw") == "zlib"
    assert negotiate_codec("RAW") == "raw"
    best = negotiate_codec("zstd,zlib,raw")
    assert best == ("zstd" if HAVE_ZSTD else "zlib")

    # Rebuild a v2 raw frame as the v1 layout: v1 magic, a header with no
    # codec/CRC fields, the uncompressed array section.
    payload = synthetic_decode_payload(
        CFG, block_size=8, kv_dtype="int8", prompt_len=9, max_new_tokens=3
    )
    v2 = payload_to_bytes(payload, codec="raw")
    hlen = int.from_bytes(v2[8:16], "little")
    header = _json.loads(v2[16: 16 + hlen])
    body = v2[16 + hlen:]
    for key in ("codec", "crc32", "raw_nbytes", "body_nbytes"):
        header.pop(key)
    legacy_header = _json.dumps(header, separators=(",", ":")).encode()
    v1 = b"".join([
        PAYLOAD_MAGIC_V1,
        len(legacy_header).to_bytes(8, "little"), legacy_header, body,
    ])
    assert not v1.startswith(PAYLOAD_MAGIC)
    back = payload_from_bytes(v1)
    assert back["meta"] == payload["meta"]
    for a, b in zip(payload["layers"], back["layers"]):
        for name in a:
            np.testing.assert_array_equal(a[name], b[name])


def test_export_import_roundtrip_token_identical(
    setup, dense_engine, paged_engine, migration_target
):
    """ACCEPTANCE (ISSUE 15): a generation prefixed + partially decoded on
    replica A and continued on replica B is token-identical to the same
    request served monolithically — greedy exact AND seeded sampling
    exact (the RNG key rides the payload)."""
    params, prompts = setup
    src, dst = paged_engine, migration_target
    for prompt, kn in (
        (prompts[2], dict(temperature=0.0)),
        (prompts[3], dict(temperature=0.9, top_k=7, top_p=0.8, seed=3)),
    ):
        ref = _run(dense_engine, prompt, max_new_tokens=8, **kn)
        event = src.admit(prompt, max_new_tokens=8, **kn)
        out = [event.token]
        slot = event.slot
        for _ in range(3):  # migrate MID-generation, not at a boundary
            event = next(e for e in src.tick() if e.slot == slot)
            out.append(event.token)
        payload = payload_from_bytes(
            payload_to_bytes(src.export_slot(slot))
        )
        src.release(slot)
        slot_b = dst.import_slot(payload)
        out += _continue_on(dst, slot_b, event)
        assert out == ref, f"migration divergence for {kn}"


def test_import_mid_prefill_frontier_resumes(setup, dense_engine):
    """A payload exported MID-CHUNKED-PREFILL (frontier between chunks)
    resumes on the importer — remaining chunks run there, then decode —
    token-identical to the dense whole-prompt run."""
    params, prompts = setup
    src = PagedEngine(
        params, CFG, slots=1, block_size=8, min_bucket=8, prefill_chunk=8
    )
    dst = PagedEngine(
        params, CFG, slots=1, block_size=8, min_bucket=8, prefill_chunk=8
    )
    prompt = prompts[3] + [5, 6]  # 21 tokens = 3 chunks of 8
    ref = _run(dense_engine, prompt, max_new_tokens=6, temperature=0.0)
    slot = src.begin(prompt, max_new_tokens=6, temperature=0.0)
    assert src.prefill_step(slot) is None  # one chunk in, frontier at 8
    payload = src.export_slot(slot)
    assert payload["meta"]["decoding"] is False
    assert payload["meta"]["next_pos"] == 8
    src.release(slot)
    slot_b = dst.import_slot(payload_from_bytes(payload_to_bytes(payload)))
    event = dst.prefill_step(slot_b)
    while event is None:
        event = dst.prefill_step(slot_b)
    out = [event.token] + _continue_on(dst, slot_b, event)
    assert out == ref


def test_export_never_mutates_shared_radix_blocks(setup, paged_engine):
    """ACCEPTANCE (satellite): exporting a slot whose chain includes
    radix-shared blocks is strictly read-only — refcounts, the radix
    index, and the shared blocks' pool rows are bitwise untouched."""
    params, prompts = setup
    engine = paged_engine
    base = prompts[3]  # 19 tokens: 2 full blocks -> radix-indexed
    _run(engine, base + [33, 34], max_new_tokens=4, temperature=0.0)
    slot = engine.begin(base + [41, 42, 43], max_new_tokens=4,
                        temperature=0.0)
    assert engine.slot_shared_len(slot) == 16
    shared_ids = engine._slots[slot].block_ids[:2]
    refs_before = [engine.allocator.refcount(b) for b in shared_ids]
    rows_before = [
        np.asarray(engine._pool[0]["k"][b]).copy() for b in shared_ids
    ]
    nodes_before = len(engine.prefix_cache)
    event = engine.prefill_step(slot)
    while event is None:
        event = engine.prefill_step(slot)
    payload = engine.export_slot(slot)
    # Only WRITTEN blocks ship (position 22 -> 3 of the 4-block chain).
    written = -(-int(engine._positions[slot]) // engine.block_size)
    assert payload["meta"]["n_blocks"] == written
    assert written < len(engine._slots[slot].block_ids)
    assert [engine.allocator.refcount(b) for b in shared_ids] == refs_before
    assert len(engine.prefix_cache) >= nodes_before
    for b, before in zip(shared_ids, rows_before):
        np.testing.assert_array_equal(
            np.asarray(engine._pool[0]["k"][b]), before
        )
    engine.release(slot)


def test_export_import_int8_scales_survive_and_decode_stays_coherent(setup):
    """ACCEPTANCE (satellite): int8 payloads carry the per-block-per-head
    scale rows bitwise; the importing slot's continued decode
    (rescale-on-grow against the imported scales) is token-identical to
    the unmigrated int8 engine — at act width this also pins the paged
    pool rows themselves round-tripping bitwise."""
    params, prompts = setup
    src = PagedEngine(params, CFG, slots=2, block_size=8, min_bucket=8,
                      kv_dtype="int8")
    dst = PagedEngine(params, CFG, slots=2, block_size=8, min_bucket=8,
                      kv_dtype="int8")
    mono = PagedEngine(params, CFG, slots=2, block_size=8, min_bucket=8,
                       kv_dtype="int8")
    for prompt, kn in (
        (prompts[2], dict(temperature=0.0)),
        (prompts[3], dict(temperature=0.9, top_k=7, top_p=0.8, seed=3)),
    ):
        ref = _run(mono, prompt, max_new_tokens=8, **kn)
        event = src.admit(prompt, max_new_tokens=8, **kn)
        out = [event.token]
        slot = event.slot
        # Decode past a block boundary so rescale-on-grow has happened.
        for _ in range(3):
            event = next(e for e in src.tick() if e.slot == slot)
            out.append(event.token)
        payload = src.export_slot(slot)
        n_written = payload["meta"]["n_blocks"]
        src_ids = list(src._slots[slot].block_ids)[:n_written]
        slot_b = dst.import_slot(
            payload_from_bytes(payload_to_bytes(payload))
        )
        # Written blocks (rows + scale rows) round-trip bitwise; the
        # reservation tail is re-reserved locally, never shipped.
        dst_ids = list(dst._slots[slot_b].block_ids)[:n_written]
        for li in (0, len(src._pool) - 1):
            for name in ("k", "v", "k_scale", "v_scale"):
                np.testing.assert_array_equal(
                    np.asarray(src._pool[li][name][np.asarray(src_ids)]),
                    np.asarray(dst._pool[li][name][np.asarray(dst_ids)]),
                    err_msg=f"layer {li} {name} rows diverged in transit",
                )
        src.release(slot)
        out += _continue_on(dst, slot_b, event)
        assert out == ref, f"int8 migration divergence for {kn}"


def test_decode_role_import_path_compiles_tick_plus_inject_only(setup):
    """ACCEPTANCE (compile bound): an engine fed ONLY synthetic grafts —
    the decode-role replica's whole life — compiles exactly the tick +
    the per-block inject program.  The chunk ladder never builds, at
    both pool widths, and chain length never adds programs."""
    params, _ = setup
    for kv_dtype in (None, "int8"):
        engine = PagedEngine(
            params, CFG, slots=2, block_size=8, min_bucket=8,
            kv_dtype=kv_dtype,
        )
        for plen in (5, 9, 17):  # 1-, 2-, and 3-block chains
            slot = engine.import_slot(
                synthetic_decode_payload(
                    CFG, block_size=8, kv_dtype=engine.kv_dtype,
                    prompt_len=plen, max_new_tokens=3,
                )
            )
            while engine._active[slot]:
                engine.tick()
        breakdown = {
            name: getattr(engine, name)._cache_size()
            for name in ("_chunk_jit", "_tick_jit", "_copy_jit",
                         "_extract_jit", "_inject_jit")
        }
        assert engine.compiled_programs() == 2, (
            f"decode-role bound broken at kv_dtype={kv_dtype}: "
            f"{engine.compiled_programs()} programs ({breakdown})"
        )
        assert engine._chunk_jit._cache_size() == 0


def test_import_validation_and_block_exhaustion(setup, paged_engine):
    """Geometry mismatches are refused before any block is allocated; a
    dry pool raises NoFreeBlocksError and the retry lands cleanly once
    blocks free (no leaked blocks/slots from the failed attempt)."""
    params, _ = setup
    engine = paged_engine
    good = synthetic_decode_payload(
        CFG, block_size=8, kv_dtype=engine.kv_dtype, prompt_len=9,
        max_new_tokens=2,
    )
    bad = {"meta": dict(good["meta"], block_size=16), "layers": good["layers"]}
    with pytest.raises(ValueError, match="block_size"):
        engine.import_slot(bad)
    bad = {"meta": dict(good["meta"], kv_dtype="int8"),
           "layers": good["layers"]}
    with pytest.raises(ValueError, match="kv_dtype"):
        engine.import_slot(bad)
    # Mid-prefill frontiers must be block-aligned on the importer.
    bad = {"meta": dict(good["meta"], decoding=False, next_pos=5),
           "layers": good["layers"]}
    with pytest.raises(ValueError, match="block-aligned"):
        engine.import_slot(bad)

    small = PagedEngine(params, CFG, slots=2, block_size=8, num_blocks=4,
                        min_bucket=8, prefix_cache=False)
    hog = small.begin([1] * 9, max_new_tokens=5)  # takes 2 of 3 blocks
    free_before = small.allocator.free_count
    with pytest.raises(NoFreeBlocksError):
        small.import_slot(good)  # needs 2 blocks, 1 free
    assert small.allocator.free_count == free_before, "failed import leaked"
    assert small.free_slots == 1
    small.release(hog)
    slot = small.import_slot(good)
    assert small._active[slot]


def test_spec_engine_migration_greedy_parity(setup):
    """Speculative decoding composes with migration (ISSUE 15): the
    importing SpecEngine re-prefills its draft cache from the grafted
    prefix's token history, and greedy output stays token-identical to
    the unmigrated paged run (greedy spec == greedy plain by the
    acceptance rule)."""
    from bpe_transformer_tpu.serving.spec.draft import DraftSpec
    from bpe_transformer_tpu.serving.spec.engine import SpecEngine

    params, prompts = setup
    spec_kwargs = dict(
        draft=DraftSpec(truncate_layers=1), speculate_k=2, slots=2,
        block_size=8, min_bucket=8,
    )
    src = SpecEngine(params, CFG, **spec_kwargs)
    dst = SpecEngine(params, CFG, **spec_kwargs)
    plain = PagedEngine(params, CFG, slots=2, block_size=8, min_bucket=8)
    prompt = prompts[3]
    ref = _run(plain, prompt, max_new_tokens=10, temperature=0.0)

    event = src.admit(prompt, max_new_tokens=10, temperature=0.0)
    out = [event.token]
    slot = event.slot
    events = [e for e in src.tick() if e.slot == slot]  # one spec tick
    out += [e.token for e in events]
    event = events[-1]
    assert not event.finished
    payload = src.export_slot(
        slot, {"history": list(prompt) + out}
    )
    src.release(slot)
    # Without the history a speculative graft must refuse loudly.
    headless = {"meta": {k: v for k, v in payload["meta"].items()
                         if k != "history"},
                "layers": payload["layers"]}
    with pytest.raises(ValueError, match="history"):
        dst.import_slot(headless)
    slot_b = dst.import_slot(payload)
    done = False
    while not done:
        for e in dst.tick():
            if e.slot != slot_b:
                continue
            out.append(e.token)
            done = bool(e.finished)
    assert out == ref


def test_migration_fixture_pins_report_and_compare_gate():
    """The committed migration fixture (schema check #5's pinned wire
    format) renders the report's kv-migration section and feeds the
    migration_p99_s / decode_p99_disagg compare-gate rows (ISSUE 15)."""
    from bpe_transformer_tpu.telemetry.report import (
        extract_compare_metrics,
        load_records,
        render_report,
        summarize,
    )

    records = load_records(
        REPO / "tests" / "fixtures" / "migration_tiny.jsonl"
    )
    report = render_report(records)
    assert "== kv migration (4 moves) ==" in report
    assert "export 1  import 2  evacuate 1" in report
    assert "total p99 0.044s" in report
    assert "disaggregated decode p99 0.9s" in report

    metrics = extract_compare_metrics(summarize(records))
    assert metrics["migration_p99_s"] == (0.044, "lower")
    assert metrics["decode_p99_disagg"] == (0.9, "lower")


def test_monitor_folds_migration_records():
    """`bpe-tpu monitor` folds kind="migration" records into the kv line
    (satellite: migration counters on the monitor's kv view)."""
    from bpe_transformer_tpu.telemetry.monitor import (
        fold_records,
        render_frame,
    )

    records = [
        json.loads(ln)
        for ln in (
            REPO / "tests" / "fixtures" / "migration_tiny.jsonl"
        ).read_text().splitlines()
    ]
    state = fold_records(records)
    assert state["kv_migrations_out"] == 2  # export + evacuate
    assert state["kv_migrations_in"] == 2
    assert state["kv_migration_bytes"] == 147456 * 2 + 98304 * 2
    frame = render_frame(state, "fixture")
    assert "mig 2out/2in" in frame


# -------------------------------------------------------- warmup --train


@pytest.mark.slow
def test_warmup_train_cli_warms_supervisor_respawn(tmp_path):
    """ACCEPTANCE (satellite, ROADMAP item 5 remainder): `bpe-tpu warmup
    --train` AOT-compiles the training step into the persistent cache,
    and a REAL `bpe-tpu train --compile-cache` run with matching flags is
    served from disk — its resources records count cache hits, i.e. the
    supervisor respawn loop restarts warm."""
    cache_dir = tmp_path / "xla_cache"
    data = tmp_path / "tokens.bin"
    np.random.default_rng(0).integers(
        0, 200, size=4096, dtype=np.uint16
    ).tofile(data)
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "XLA_FLAGS": "",
           "PYTHONPATH": str(REPO)}
    flags = ["--preset", "ts-test", "--batch-size", "4", "--steps", "3",
             "--log-every", "1"]

    proc = subprocess.run(
        [sys.executable, "-m", "bpe_transformer_tpu.training.cli",
         "warmup", "--train", "--compile-cache", str(cache_dir),
         "--preset", "ts-test", "--batch-size", "4", "--steps", "3"],
        capture_output=True, text=True, timeout=600, env=env, cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    summary = json.loads(proc.stdout.strip().splitlines()[-1])
    assert summary["mode"] == "train"
    assert summary["programs_compiled"] == 2  # train step + eval step
    assert summary["cache_hits"] == 0
    assert any(cache_dir.rglob("*")), "warmup --train wrote no cache entries"

    jsonl = tmp_path / "train.jsonl"
    proc = subprocess.run(
        [sys.executable, "-m", "bpe_transformer_tpu.training.cli",
         "train", "--data", str(data), "--compile-cache", str(cache_dir),
         "--metrics-jsonl", str(jsonl),
         "--eval-every", "1000", "--checkpoint-every", "1000"] + flags,
        capture_output=True, text=True, timeout=600, env=env, cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    records = [json.loads(ln) for ln in jsonl.read_text().splitlines()]
    hits = [
        r.get("compile_cache_hits")
        for r in records
        if r.get("kind") == "resources"
        and r.get("compile_cache_hits") is not None
    ]
    assert hits and max(hits) > 0, (
        "the warmed train run paid cold compiles (no cache hits in its "
        f"resources records: {hits})"
    )
