"""Paged KV memory: block allocator, radix prefix cache, paged-engine
parity with the dense slot pool, chunked prefill scheduling, and the
kvpool telemetry surface.

The correctness bar (ISSUE 8): the paged engine is **token-identical** to
the dense engine for the same requests/seeds — paging, prefix sharing,
and chunked prefill change memory and scheduling, never tokens.
"""

import dataclasses
import json
import os
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

import jax

from bpe_transformer_tpu.models import TS_TEST_CONFIG, init_params
from bpe_transformer_tpu.serving import Request, ServingEngine
from bpe_transformer_tpu.serving.engine import SlotPoolEngine
from bpe_transformer_tpu.serving.kvpool.blocks import (
    BlockAllocator,
    NoFreeBlocksError,
)
from bpe_transformer_tpu.serving.kvpool.paged_engine import PagedEngine
from bpe_transformer_tpu.serving.kvpool.radix import RadixPrefixCache
from bpe_transformer_tpu.serving.scheduler import PrefillBudget

pytestmark = pytest.mark.serving

REPO = Path(__file__).resolve().parent.parent

CFG = dataclasses.replace(TS_TEST_CONFIG, vocab_size=128, context_length=32)


@pytest.fixture(scope="module")
def setup():
    params = init_params(jax.random.PRNGKey(0), CFG)
    rng = np.random.default_rng(0)
    prompts = [
        [int(t) for t in rng.integers(0, CFG.vocab_size, size=n)]
        for n in (3, 7, 12, 19)
    ]
    return params, prompts


@pytest.fixture(scope="module")
def dense_engine(setup):
    params, _ = setup
    return SlotPoolEngine(params, CFG, slots=2, min_bucket=8)


@pytest.fixture(scope="module")
def paged_engine(setup):
    # Shared across the parity + bounded-compile tests: per-engine jit
    # caches make engines the expensive resource in this module (same
    # policy as test_serving).
    params, _ = setup
    return PagedEngine(params, CFG, slots=2, block_size=8, min_bucket=8)


@pytest.fixture(scope="module")
def chunked_engine(setup):
    params, _ = setup
    return PagedEngine(
        params, CFG, slots=2, block_size=8, min_bucket=8, prefill_chunk=8
    )


def _run(engine, prompt, **knobs):
    event = engine.admit(prompt, **knobs)
    out = [event.token]
    slot = event.slot
    while not event.finished:
        events = engine.tick()
        event = next(e for e in events if e.slot == slot)
        out.append(event.token)
    return out


# ------------------------------------------------------------- allocator


def test_block_allocator_refcounts_and_free_list():
    alloc = BlockAllocator(num_blocks=5, block_size=8)
    assert alloc.usable_blocks == 4 and alloc.free_count == 4
    a = alloc.alloc(2)
    assert 0 not in a, "the trash block must never be handed out"
    alloc.ref([a[0]])  # shared now
    assert alloc.shared_count == 1
    assert alloc.deref([a[0], a[1]]) == 1  # a[1] freed, a[0] still shared->1
    assert alloc.deref([a[0]]) == 1
    assert alloc.free_count == 4 and alloc.shared_count == 0
    with pytest.raises(NoFreeBlocksError):
        alloc.alloc(5)
    assert alloc.free_count == 4, "a failed alloc must not leak blocks"
    with pytest.raises(ValueError):
        alloc.deref([0])


def test_radix_cache_match_insert_evict():
    alloc = BlockAllocator(num_blocks=9, block_size=4)
    cache = RadixPrefixCache(alloc)
    prompt = list(range(11))  # 2 full blocks + a 3-token tail
    blocks = alloc.alloc(3)
    assert cache.insert(prompt, blocks) == 2  # only FULL blocks indexed
    # Matching the same prompt reuses both full blocks (tail stays live).
    matched = cache.match(prompt)
    assert matched == blocks[:2]
    assert alloc.refcount(blocks[0]) == 3  # owner + cache + new match
    # A 9-token prompt sharing one block matches exactly that block —
    # never the whole prompt (the last token must be computed).
    assert cache.match(prompt[:4] + [99, 98, 97, 96, 95]) == blocks[:1]
    # Counters are charged per ADMISSION (engine calls charge), never by
    # match itself — a parked admission's retries must not inflate them.
    assert cache.gauges()["prefix_cache_hits"] == 0
    cache.charge(11, 8)
    cache.charge(9, 4)
    assert cache.gauges()["prefix_cache_hits"] == 8 + 4
    assert cache.gauges()["prefix_cache_misses"] == 3 + 5
    # Release every non-cache reference; eviction then frees LRU leaves.
    alloc.deref(matched)
    alloc.deref(blocks[:1])
    alloc.deref(blocks)
    free_before = alloc.free_count
    assert cache.evict(1) == 1
    assert alloc.free_count == free_before + 1
    # The interior block (prefix of nothing now, but parent of none after
    # the leaf died) becomes evictable next.
    assert cache.evict(5) == 1
    assert len(cache) == 0


def test_prefill_budget_policy():
    budget = PrefillBudget(16)
    budget.start_tick()
    assert budget.admits(64), "the first chunk is always admitted"
    budget.spend(64)
    assert not budget.admits(1)
    budget.start_tick()
    assert budget.admits(8)
    budget.spend(8)
    assert budget.admits(8) and not budget.admits(9)
    assert PrefillBudget(None).admits(10**9)
    with pytest.raises(ValueError):
        PrefillBudget(0)


# ------------------------------------------------------ engine parity


def test_paged_parity_with_dense_engine(setup, dense_engine, paged_engine):
    """ACCEPTANCE: the paged engine's outputs are token-identical to the
    dense slot-pool engine for the same requests/seeds — across greedy
    AND seeded temperature/top-k/top-p sampling."""
    params, prompts = setup
    paged = paged_engine
    knobs = [
        dict(temperature=0.0),
        dict(temperature=0.9, top_k=7, top_p=0.8, seed=3),
        dict(temperature=1.0, top_k=2, seed=5),
        dict(temperature=0.7, seed=1),
    ]
    for prompt, kn in zip(prompts, knobs):
        assert _run(paged, prompt, max_new_tokens=8, **kn) == _run(
            dense_engine, prompt, max_new_tokens=8, **kn
        ), f"paged/dense divergence for {kn}"


def test_paged_parity_through_shared_prefix(setup, dense_engine, paged_engine):
    """ACCEPTANCE: radix prefix sharing reuses cached blocks (hits > 0,
    fewer blocks allocated) and the reusing request's outputs stay
    token-identical to the dense engine."""
    params, prompts = setup
    paged = paged_engine
    base = prompts[3]  # 19 tokens: 2 full blocks of 8 + a tail
    first = base + [5, 6]
    second = base + [9, 1, 2]

    assert _run(paged, first, max_new_tokens=6, temperature=0.0) == _run(
        dense_engine, first, max_new_tokens=6, temperature=0.0
    )
    hits_before = paged.gauges()["prefix_cache_hits"]
    slot = paged.begin(second, max_new_tokens=6, temperature=0.0)
    assert paged.slot_shared_len(slot) == 16, "2 full blocks must be reused"
    event = paged.prefill_step(slot)
    while event is None:
        event = paged.prefill_step(slot)
    out = [event.token]
    while not event.finished:
        event = next(e for e in paged.tick() if e.slot == slot)
        out.append(event.token)
    assert out == _run(dense_engine, second, max_new_tokens=6, temperature=0.0)
    assert paged.gauges()["prefix_cache_hits"] == hits_before + 16


def test_paged_parity_with_chunked_prefill(setup, dense_engine, chunked_engine):
    """Chunked prefill (8-token chunks over a 21-token prompt) produces
    the same tokens as the dense whole-prompt prefill."""
    params, prompts = setup
    chunked = chunked_engine
    prompt = prompts[3] + [5, 6]
    for kn in (
        dict(temperature=0.0),
        dict(temperature=0.9, top_k=7, top_p=0.8, seed=3),
    ):
        assert _run(chunked, prompt, max_new_tokens=6, **kn) == _run(
            dense_engine, prompt, max_new_tokens=6, **kn
        )


def test_paged_bounded_compilation_and_block_lifecycle(
    setup, paged_engine, chunked_engine
):
    """ACCEPTANCE: the paged engine compiles at most len(buckets) + 1
    programs over mixed lengths/knobs (the dense engine's contract,
    extended to the paged path), and releases return every block.  Runs
    against the module engines AFTER the parity tests have pushed their
    own mixed lengths/knobs through — the bound covers everything the
    engine has ever served."""
    params, prompts = setup
    engine = paged_engine
    assert engine.buckets == (8, 16, 32)
    for prompt, kn in zip(
        prompts + [prompts[0]],
        [
            dict(temperature=0.0),
            dict(temperature=0.7, top_k=5),
            dict(temperature=1.3, top_p=0.9),
            dict(temperature=0.9, top_k=7, top_p=0.8, seed=3),
            dict(temperature=0.5),
        ],
    ):
        _run(engine, prompt, max_new_tokens=4, **kn)
    assert engine.compiled_programs() <= len(engine.buckets) + 1
    # All slots retired: only prefix-cache references keep blocks busy.
    gauges = engine.gauges()
    held = gauges["kv_blocks_total"] - gauges["kv_blocks_free"]
    assert held == len(engine.prefix_cache)
    # Chunked ladder shrinks the bound, never grows it.
    assert chunked_engine.buckets == (8,)
    _run(chunked_engine, prompts[2], max_new_tokens=2, temperature=0.0)
    assert chunked_engine.compiled_programs() <= len(chunked_engine.buckets) + 1


def test_paged_validation_errors(setup):
    params, _ = setup
    with pytest.raises(ValueError, match="block_size"):
        PagedEngine(params, CFG, block_size=7)
    with pytest.raises(ValueError, match="prefill_chunk"):
        PagedEngine(params, CFG, block_size=8, prefill_chunk=12)
    engine = PagedEngine(params, CFG, slots=1, block_size=8, num_blocks=3)
    # 2 usable blocks = 16 positions: a full-context request can't ever fit.
    with pytest.raises(ValueError, match="KV blocks"):
        engine.begin([1] * 20, max_new_tokens=8)
    with pytest.raises(ValueError, match="no room"):
        engine.begin([1] * 32, max_new_tokens=4)
    with pytest.raises(RuntimeError, match="no free slot"):
        engine.begin([1, 2], max_new_tokens=2)
        engine.begin([1, 2], max_new_tokens=2)


def test_block_starved_pool_raises_then_recovers(setup):
    """A pool too small for two concurrent requests raises
    NoFreeBlocksError for the second; after the first releases, the same
    begin succeeds — the backpressure loop the serving backlog drives."""
    params, prompts = setup
    engine = PagedEngine(
        params, CFG, slots=2, block_size=8, num_blocks=5, prefix_cache=False
    )
    slot = engine.begin(prompts[2], max_new_tokens=20, temperature=0.0)
    with pytest.raises(NoFreeBlocksError):
        engine.begin(prompts[1], max_new_tokens=20)
    engine.release(slot)
    slot2 = engine.begin(prompts[1], max_new_tokens=20, temperature=0.0)
    assert engine.slot_shared_len(slot2) == 0


# ---------------------------------------------------- serving integration


def test_block_starved_backlog_parks_expires_and_drains(setup):
    """ServingEngine over a block-starved paged pool, driven by hand: a
    second request parks in the admission backlog; a parked request whose
    deadline lapses fails with "deadline" (the deadline contract follows
    the request out of the scheduler); a deadline-less parked request
    completes once the first retires — no failure, no deadlock."""
    params, prompts = setup
    serving = ServingEngine(
        params, CFG, slots=2, min_bucket=8, paged=True, block_size=8,
        num_kv_blocks=5, prefix_cache=False,
    )
    serving._running = True  # drive the worker loop by hand
    h1 = serving.submit(
        Request(
            prompt_ids=tuple(prompts[2]), max_new_tokens=16,
            temperature=0.0,
        )
    )
    serving._step()  # h1 admits and takes every usable block
    h_dead = serving.submit(
        Request(
            prompt_ids=tuple(prompts[1]), max_new_tokens=16,
            deadline_s=0.01,
        )
    )
    h2 = serving.submit(
        Request(
            prompt_ids=tuple(prompts[1]), max_new_tokens=16,
            temperature=0.0,
        )
    )
    serving._step()  # h_dead popped, block-starved -> parked
    assert serving._admit_backlog, "expected the admission to park"
    time.sleep(0.02)
    serving._step()
    assert h_dead.result(timeout=5).finish_reason == "deadline"
    for _ in range(200):
        serving._step()
        if h1._entry.done.is_set() and h2._entry.done.is_set():
            break
    assert h1.result(timeout=5).finish_reason == "length"
    # The parked survivor was admitted once h1's retirement freed blocks.
    assert h2.result(timeout=5).finish_reason == "length"
    assert len(h2.result().token_ids) >= 1
    serving._running = False
    serving.close()


def test_serving_rejects_request_that_can_never_fit(setup):
    params, prompts = setup
    serving = ServingEngine(
        params, CFG, slots=1, min_bucket=8, paged=True, block_size=8,
        num_kv_blocks=3,
    )
    serving._running = True
    with pytest.raises(ValueError, match="KV blocks"):
        serving.submit(Request(prompt_ids=tuple(range(20)), max_new_tokens=8))


def test_chunked_prefill_interleaves_decode_ticks(setup):
    """ACCEPTANCE (offline, deterministic): under a prefill-token budget,
    a long prompt's chunked prefill interleaves with decode ticks — the
    already-decoding request keeps receiving a token every worker step
    instead of stalling until the whole prefill lands."""
    params, prompts = setup
    serving = ServingEngine(
        params, CFG, slots=2, min_bucket=8, paged=True, block_size=8,
        prefill_chunk=8, prefill_token_budget=8,
    )
    serving._running = True  # drive the worker loop by hand
    h1 = serving.submit(
        Request(prompt_ids=(1, 2, 3), max_new_tokens=24, temperature=0.0)
    )
    serving._step()  # admit + one-chunk prefill + first tick
    assert serving.engine.active_count == 1

    # 24-token prompt -> 3 chunks of 8 under the budget: 3 worker steps.
    serving.submit(
        Request(
            prompt_ids=tuple(int(t) for t in prompts[3]) + (1, 2, 3, 4, 5),
            max_new_tokens=2, temperature=0.0,
        )
    )
    ticks_before = serving.engine.ticks
    tokens_before = len(serving._slot_entries[h1._entry.slot].tokens)
    serving._step()  # admits the long prompt + runs chunk 1 of 3 + a tick
    assert serving._prefill_entries, "prefill must span multiple steps"
    steps = 1
    while serving._prefill_entries and steps < 10:
        serving._step()
        steps += 1
    assert steps == 3, f"expected 3 budgeted chunk steps, took {steps}"
    # EVERY one of those steps also ran a decode tick: no starvation.
    assert serving.engine.ticks == ticks_before + 3
    assert (
        len(serving._slot_entries[h1._entry.slot].tokens)
        == tokens_before + 3
    )
    # Drain the rest so close() isn't cancelling live work.
    while serving._slot_entries or serving._prefill_entries:
        serving._step()
    serving._running = False
    serving.close()


def test_serving_paged_telemetry_kvpool_records(setup):
    """A paged serving run emits schema-valid kind="kvpool" records and
    the kv gauges reach stats()/statusz()/Prometheus."""
    from bpe_transformer_tpu.telemetry import Telemetry, validate_record
    from bpe_transformer_tpu.telemetry.monitor import parse_prometheus

    params, prompts = setup
    records = []
    telemetry = Telemetry(sink=records.append)
    with ServingEngine(
        params, CFG, slots=2, min_bucket=8, paged=True, block_size=8,
        telemetry=telemetry, engine_record_every_s=0.0,
    ) as serving:
        base = prompts[3]
        # Serialized on purpose: the second request must arrive AFTER the
        # first's prefill has indexed its blocks (two racing identical
        # prefills legitimately miss the dedup — documented behavior).
        serving.generate(base + [5], max_new_tokens=4, temperature=0.0)
        serving.generate(base + [9, 1], max_new_tokens=4, temperature=0.0)
        stats = serving.stats()
        page = serving.statusz()
        prom = parse_prometheus(serving.prometheus_metrics())

    kvpool = [r for r in records if r.get("kind") == "kvpool"]
    assert kvpool, "paged run emitted no kvpool records"
    for record in kvpool:
        assert validate_record(record) == []
    assert kvpool[-1]["prefix_hits"] > 0
    assert kvpool[-1]["blocks_total"] == stats["kv_blocks_total"]

    assert stats["engine_kind"] == "paged"
    assert stats["prefix_cache_hits"] == 16
    assert stats["kv_blocks_free"] > 0
    assert page["kvpool"]["kv_blocks_total"] == stats["kv_blocks_total"]
    assert page["engine_kind"] == "paged"
    assert page["draining"] is False
    json.dumps(page)

    assert prom["bpe_tpu_kv_blocks_total"] == stats["kv_blocks_total"]
    assert prom["bpe_tpu_prefix_cache_hits_total"] == 16
    assert prom["bpe_tpu_kv_blocks_free"] == stats["kv_blocks_free"]
    assert "bpe_tpu_prefill_pending_tokens" in prom


def test_kvpool_fixture_pins_report_and_compare_gate():
    """The committed kvpool fixture renders the report's kv-pool section
    and feeds the prefix_hit_rate / kv_blocks_free compare-gate metrics."""
    from bpe_transformer_tpu.telemetry.report import (
        extract_compare_metrics,
        load_records,
        render_report,
        summarize,
    )

    records = load_records(REPO / "tests" / "fixtures" / "kvpool_tiny.jsonl")
    report = render_report(records)
    assert "== kv pool (3 samples) ==" in report
    assert "hit rate 60.0%" in report
    assert "free last 52 (min 31)" in report
    assert "chunked-prefill backlog max 128" in report

    metrics = extract_compare_metrics(summarize(records))
    assert metrics["prefix_hit_rate"] == (0.6, "higher")
    assert metrics["kv_blocks_free"] == (31.0, "higher")


def test_monitor_folds_kvpool_records():
    from bpe_transformer_tpu.telemetry.monitor import (
        fold_records,
        render_frame,
    )

    state = fold_records(
        [
            {"kind": "manifest", "run_kind": "serve", "time_utc": "x",
             "host": "h"},
            {"kind": "kvpool", "t": 1.0, "blocks_total": 64,
             "blocks_free": 31, "blocks_shared": 6, "prefix_hits": 96,
             "prefix_misses": 128, "prefix_hit_rate": 0.428571,
             "prefill_pending_tokens": 40},
        ]
    )
    assert state["kv_blocks_free"] == 31
    frame = render_frame(state, "test")
    assert "blocks 31/64 free" in frame
    assert "prefix hit 43%" in frame
    assert "prefill backlog 40" in frame


# ----------------------------------------------------------- warmup CLI


@pytest.mark.slow
def test_warmup_cli_two_process_cache_hits(tmp_path):
    """ACCEPTANCE (ROADMAP item 5 stub): `bpe-tpu warmup` AOT-compiles
    the serving ladder into the persistent compile cache; a second
    process (the restarted replica) is served from disk — its cache-hit
    counter climbs while the cold one's stays 0."""
    cache_dir = tmp_path / "xla_cache"

    def run():
        proc = subprocess.run(
            [
                sys.executable, "-m", "bpe_transformer_tpu.training.cli",
                "warmup", "--compile-cache", str(cache_dir),
                "--preset", "ts-test", "--paged", "--block-size", "8",
                "--slots", "2",
            ],
            capture_output=True, text=True, timeout=300,
            env={**os.environ, "JAX_PLATFORMS": "cpu", "XLA_FLAGS": "",
                 "PYTHONPATH": str(REPO)},
            cwd=str(REPO),
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        return json.loads(proc.stdout.strip().splitlines()[-1])

    cold = run()
    assert cold["cache_hits"] == 0
    assert cold["programs_compiled"] <= len(cold["buckets"]) + 1
    assert any(cache_dir.rglob("*")), "warmup wrote no cache entries"
    warm = run()
    assert warm["cache_hits"] > 0
