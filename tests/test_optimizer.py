"""AdamW vs torch.optim.AdamW (1000-step trace) + cosine schedule pins."""

import math

import numpy as np
import torch

import jax
import jax.numpy as jnp

from bpe_transformer_tpu.optim import (
    adamw_init,
    adamw_update,
    cosine_schedule,
    cosine_schedule_jax,
)


def test_adamw_matches_torch_1000_steps():
    """Replicates the reference's optimizer trace (`test_optimizer.py:7-49`):
    a bias-free Linear(3, 2) regression, 1000 AdamW steps, weights must match
    torch's AdamW within 1e-4."""
    torch.manual_seed(42)
    model = torch.nn.Linear(3, 2, bias=False)
    w0 = model.weight.detach().clone()
    opt = torch.optim.AdamW(
        model.parameters(), lr=1e-3, weight_decay=0.01, betas=(0.9, 0.999), eps=1e-8
    )
    xs = []
    for _ in range(1000):
        opt.zero_grad()
        x = torch.rand(3)
        xs.append(x.numpy().copy())
        y_hat = model(x)
        y = torch.tensor([x[0] + x[1], -x[2]])
        loss = ((y - y_hat) ** 2).sum()
        loss.backward()
        opt.step()
    torch_weights = model.weight.detach().numpy()

    # Same trace through the pure-JAX AdamW.
    params = {"w": jnp.asarray(w0.numpy())}
    state = adamw_init(params)

    def loss_fn(p, x):
        y_hat = p["w"] @ x
        y = jnp.array([x[0] + x[1], -x[2]])
        return ((y - y_hat) ** 2).sum()

    @jax.jit
    def step(p, s, x):
        g = jax.grad(loss_fn)(p, x)
        return adamw_update(p, g, s, lr=1e-3, weight_decay=0.01)

    for x in xs:
        params, state = step(params, state, jnp.asarray(x))

    np.testing.assert_allclose(np.asarray(params["w"]), torch_weights, atol=1e-4)


def test_cosine_schedule_exact_values():
    """The reference pins 25 exact schedule values (`test_optimizer.py:52-95`)."""
    expected = [
        0,
        0.14285714285714285,
        0.2857142857142857,
        0.42857142857142855,
        0.5714285714285714,
        0.7142857142857143,
        0.8571428571428571,
        1.0,
        0.9887175604818206,
        0.9554359905560885,
        0.9018241671106134,
        0.8305704108364301,
        0.7452476826029011,
        0.6501344202803414,
        0.55,
        0.44986557971965857,
        0.3547523173970989,
        0.26942958916356996,
        0.19817583288938662,
        0.14456400944391146,
        0.11128243951817937,
        0.1,
        0.1,
        0.1,
        0.1,
    ]
    actual = [
        cosine_schedule(
            it,
            max_learning_rate=1.0,
            min_learning_rate=0.1,
            warmup_iters=7,
            cosine_cycle_iters=21,
        )
        for it in range(25)
    ]
    np.testing.assert_allclose(actual, expected)


def test_cosine_schedule_jax_matches_host():
    its = jnp.arange(30)
    traced = cosine_schedule_jax(its, 1.0, 0.1, 7, 21)
    host = [cosine_schedule(i, 1.0, 0.1, 7, 21) for i in range(30)]
    np.testing.assert_allclose(np.asarray(traced), host, rtol=1e-6, atol=1e-7)


def test_adamw_state_is_a_pytree():
    params = {"a": jnp.ones((3,)), "nested": {"b": jnp.ones((2, 2))}}
    state = adamw_init(params)
    leaves = jax.tree_util.tree_leaves(state)
    assert len(leaves) == 1 + 2 * 2  # step + (m, v) per param leaf
    grads = jax.tree_util.tree_map(jnp.ones_like, params)
    new_params, new_state = adamw_update(params, grads, state, lr=0.1)
    assert int(new_state.step) == 1
    # params must have moved against the gradient direction
    assert float(new_params["a"][0]) < 1.0
