"""BPE trainer: exact merge-order parity with the reference fixture + speed."""

import time

import pytest

from bpe_transformer_tpu.tokenization import BPETrainer, train_bpe
from bpe_transformer_tpu.tokenization.gpt2 import decode_gpt2_token


def _load_reference_merges(path):
    merges = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            left, right = line.rstrip().split(" ")
            merges.append((decode_gpt2_token(left), decode_gpt2_token(right)))
    return merges


def test_train_bpe_exact_merge_parity(reference_fixtures):
    """Pinned: identical ordered merges + vocab on corpus.en, vocab 500.

    This locks the greedy tie-breaking (count desc, then lexicographically
    greater pair bytes) and leftmost-non-overlapping merge semantics to the
    reference's published fixture.
    """
    vocab, merges = train_bpe(
        input_path=reference_fixtures / "corpus.en",
        vocab_size=500,
        special_tokens=["<|endoftext|>"],
    )
    expected = _load_reference_merges(
        reference_fixtures / "train-bpe-reference-merges.txt"
    )
    assert merges == expected

    import json

    with open(reference_fixtures / "train-bpe-reference-vocab.json") as f:
        ref_vocab_json = json.load(f)
    ref_vocab = {
        idx: decode_gpt2_token(tok) for tok, idx in ref_vocab_json.items()
    }
    assert set(vocab.keys()) == set(ref_vocab.keys())
    assert set(vocab.values()) == set(ref_vocab.values())


def test_train_bpe_speed(reference_fixtures):
    """Reference bound: corpus.en to vocab 500 in < 1.5 s."""
    start = time.time()
    train_bpe(
        input_path=reference_fixtures / "corpus.en",
        vocab_size=500,
        special_tokens=["<|endoftext|>"],
    )
    assert time.time() - start < 1.5


def test_special_tokens_never_merged(tiny_corpus):
    vocab, merges = train_bpe(
        input_path=tiny_corpus, vocab_size=400, special_tokens=["<|endoftext|>"]
    )
    for token_bytes in vocab.values():
        if token_bytes == b"<|endoftext|>":
            continue
        assert b"<|" not in token_bytes
    # The special token occupies id 256, directly after the byte alphabet.
    assert vocab[256] == b"<|endoftext|>"


def test_vocab_growth_and_merge_consistency(tiny_corpus):
    vocab, merges = train_bpe(input_path=tiny_corpus, vocab_size=300)
    assert len(vocab) == 300
    assert len(merges) == 300 - 256
    # Every merge's concatenation must be a vocab entry, ids appended in order.
    for i, (left, right) in enumerate(merges):
        assert vocab[256 + i] == left + right


def test_merges_stop_when_no_pairs_left(tmp_path):
    path = tmp_path / "tiny.txt"
    path.write_text("ab ab ab\n")
    vocab, merges = train_bpe(input_path=path, vocab_size=400)
    # Only a handful of merges are possible; trainer must stop early.
    assert len(vocab) < 400
    assert len(merges) == len(vocab) - 256


def test_vocab_size_below_256_rejected():
    with pytest.raises(ValueError):
        BPETrainer(vocab_size=100)


def test_trainer_artifacts_roundtrip(tiny_corpus, tmp_path):
    trainer = BPETrainer(vocab_size=300, special_tokens=["<|endoftext|>"])
    trainer.train(tiny_corpus)
    trainer.save_trainer(tmp_path / "artifacts")

    from bpe_transformer_tpu.tokenization import BPETokenizer

    tok = BPETokenizer.from_files(
        tmp_path / "artifacts" / "vocab.pkl",
        tmp_path / "artifacts" / "merges.pkl",
        special_tokens=["<|endoftext|>"],
    )
    assert tok.vocab == trainer.vocab
    assert tok.merges == trainer.merges
