"""Worker for the 2-process jax.distributed localhost test.

Each process joins the cluster via ``initialize_distributed`` (the
multi-host bring-up path, parallel/mesh.py), contributes 2 virtual CPU
devices, builds ONE GLOBAL 4-device data-parallel mesh spanning both
processes, and runs one explicit-collective dp train step.  Process 0
prints the (globally pmean'd, replicated) loss for the parent test to
compare against a single-process oracle.

Invoked as: python _distributed_worker.py <coordinator> <num_procs> <pid>
"""

import os
import sys

# Must be set before jax initializes any backend: 2 virtual CPU devices per
# process -> a 4-device global cluster across the two processes.
os.environ["JAX_PLATFORMS"] = "cpu"
# Token-wise rewrite: replace only the device-count flag, keep the rest.
_flags = [
    f
    for f in os.environ.get("XLA_FLAGS", "").split()
    if not f.startswith("--xla_force_host_platform_device_count")
]
os.environ["XLA_FLAGS"] = " ".join(
    _flags + ["--xla_force_host_platform_device_count=2"]
)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    coordinator, num_procs, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])

    from bpe_transformer_tpu.parallel import initialize_distributed

    initialize_distributed(
        coordinator_address=coordinator, num_processes=num_procs, process_id=pid
    )

    import dataclasses

    import jax
    import numpy as np

    assert jax.process_count() == num_procs, jax.process_count()
    assert len(jax.devices()) == 2 * num_procs, jax.devices()

    from bpe_transformer_tpu.models import TS_TEST_CONFIG, init_params
    from bpe_transformer_tpu.optim import adamw_init
    from bpe_transformer_tpu.parallel import make_dp_train_step, make_mesh
    from bpe_transformer_tpu.training.train_step import TrainHParams
    from jax.sharding import NamedSharding, PartitionSpec

    config = dataclasses.replace(TS_TEST_CONFIG, vocab_size=512, context_length=32)
    hparams = TrainHParams(warmup_iters=2, cosine_cycle_iters=10)

    # Identical seeding in every process: params/opt replicate by
    # construction, and the global batch is assembled from the same host
    # array via make_array_from_callback (each process materializes only
    # its addressable shards).
    params = init_params(jax.random.PRNGKey(0), config)
    opt_state = adamw_init(params)
    rng = np.random.default_rng(0)
    batch = 8
    x_host = rng.integers(0, config.vocab_size, size=(batch, 32), dtype=np.int32)
    y_host = rng.integers(0, config.vocab_size, size=(batch, 32), dtype=np.int32)

    mesh = make_mesh({"data": 2 * num_procs})
    sharding = NamedSharding(mesh, PartitionSpec("data"))
    x = jax.make_array_from_callback(x_host.shape, sharding, lambda idx: x_host[idx])
    y = jax.make_array_from_callback(y_host.shape, sharding, lambda idx: y_host[idx])

    step = make_dp_train_step(config, hparams, mesh)
    params, opt_state, metrics = step(params, opt_state, x, y)
    jax.block_until_ready(metrics["loss"])
    # The loss is pmean'd over the data axis -> replicated across processes.
    loss = float(metrics["loss"].addressable_data(0))
    if pid == 0:
        print(f"DIST_LOSS {loss:.6f}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
