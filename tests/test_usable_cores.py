"""Unit tests for benchmarks/bench_tokenization.usable_cores — the gate of
the armed multi-worker capture trap (VERDICT r4 #7).  A wrong answer either
keeps the trap disarmed forever on a real multicore host or fires it with a
fantasy grid on a quota-throttled one, so the affinity ∧ cgroup-quota logic
gets direct tests."""

import pytest

from conftest import load_script_module


@pytest.fixture()
def tok_bench():
    return load_script_module(
        "bench_tok_under_test", "benchmarks/bench_tokenization.py"
    )


def _fake_cgroup(monkeypatch, mod, content):
    real_path = mod.Path

    class FakePath(type(real_path())):
        def read_text(self, *a, **k):
            if str(self) == "/sys/fs/cgroup/cpu.max":
                if isinstance(content, Exception):
                    raise content
                return content
            return super().read_text(*a, **k)

    monkeypatch.setattr(mod, "Path", FakePath)


@pytest.mark.parametrize(
    "affinity,cpu_max,expected",
    [
        (16, "max 100000", 16),        # no quota -> affinity rules
        (16, "400000 100000", 4),      # 4-CPU quota caps affinity
        (16, "50000 100000", 1),       # sub-core quota floors at 1
        (2, "800000 100000", 2),       # affinity below the quota rules
        (16, "garbage", 16),           # unparseable -> affinity fallback
        (16, OSError("no cgroup"), 16),  # cgroup v1 host -> fallback
    ],
)
def test_usable_cores(monkeypatch, tok_bench, affinity, cpu_max, expected):
    monkeypatch.setattr(
        tok_bench.os, "sched_getaffinity", lambda _: set(range(affinity))
    )
    _fake_cgroup(monkeypatch, tok_bench, cpu_max)
    assert tok_bench.usable_cores() == expected
