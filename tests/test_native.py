"""Native (C++) tokenization engine: parity with the pure-Python path.

The native engine re-implements the GPT-2 pre-tokenization regex as a
hand-rolled UTF-8 scanner and the BPE greedy merge loop in C++
(`bpe_transformer_tpu/native/src/bt_native.cpp`).  Both must be
behaviorally identical to the Python implementations, which are themselves
pinned against tiktoken and the reference
(`/root/reference/tests/test_tokenizer.py:88-413`).
"""

from __future__ import annotations

import pickle
import random

import pytest
import regex

from bpe_transformer_tpu.native import engine as native_engine
from bpe_transformer_tpu.settings import GPT2_SPLIT_PATTERN
from bpe_transformer_tpu.tokenization import BPETokenizer

pytestmark = pytest.mark.skipif(
    not native_engine.is_available(),
    reason=f"native engine unavailable: {native_engine.unavailable_reason()}",
)

_GPT2_RE = regex.compile(GPT2_SPLIT_PATTERN)

SCANNER_CASES = [
    "Hello world!  This is a test.\n",
    "don't stop 'll 've 're 'sx 'S 'D",
    "  multiple   spaces\t\ttabs\n\nnewlines  ",
    "numbers 123 mixed1a2b ¡unicode! café über 東京タワー ١٢٣",
    "trailing spaces   ",
    " ",
    "",
    "a",
    "'",
    "'' ''",
    "\n",
    "\n\na",
    " \n a",
    "🙂 emoji🙂🙂 test",
    " nbsp emsp　ideographic",
]


def _scan_native(text: str) -> list[str]:
    data = text.encode("utf-8")
    return [
        data[s:e].decode("utf-8")
        for s, e in native_engine.pretokenize_offsets(text)
    ]


@pytest.mark.parametrize("text", SCANNER_CASES)
def test_scanner_matches_regex(text):
    assert _scan_native(text) == [m.group() for m in _GPT2_RE.finditer(text)]


def test_scanner_fuzz_matches_regex():
    rng = random.Random(0)
    pool = "abc ABZ 0159 ,.!?'\"\t\n  é東🙂́א\r\x1c  "
    for _ in range(500):
        text = "".join(rng.choice(pool) for _ in range(rng.randint(0, 80)))
        assert _scan_native(text) == [m.group() for m in _GPT2_RE.finditer(text)]


@pytest.fixture(scope="module")
def toy_pair():
    """(native-enabled, python-forced) tokenizers over a small trained vocab."""
    from bpe_transformer_tpu.tokenization import BPETrainer
    import tempfile, os

    corpus = (
        "the quick brown fox jumps over the lazy dog. "
        "don't stop believing 123 числа café\n"
    ) * 50 + "<|endoftext|>\n"
    with tempfile.NamedTemporaryFile("w", suffix=".txt", delete=False) as f:
        f.write(corpus)
        path = f.name
    try:
        trainer = BPETrainer(vocab_size=400, special_tokens=["<|endoftext|>"])
        trainer.train(path, n_workers=1)
        vocab, merges = trainer.vocab, trainer.merges
    finally:
        os.unlink(path)

    tok_native = BPETokenizer(dict(vocab), list(merges), ["<|endoftext|>"])
    tok_python = BPETokenizer(dict(vocab), list(merges), ["<|endoftext|>"])
    tok_python._native_tried = True  # force the pure-Python path
    assert tok_native._native_encoder() is not None
    return tok_native, tok_python


ENCODE_CASES = [
    "the quick brown fox",
    "don't stop",
    "hello<|endoftext|>world",
    "<|endoftext|><|endoftext|>",
    "unseen bytes: ß∂ƒ 東京 🙂",
    "  spaces   and\t\ttabs\n\n",
    "",
]


@pytest.mark.parametrize("text", ENCODE_CASES)
def test_encode_parity(toy_pair, text):
    tok_native, tok_python = toy_pair
    assert tok_native.encode(text) == tok_python.encode(text)


def test_encode_fuzz_parity(toy_pair):
    tok_native, tok_python = toy_pair
    rng = random.Random(1)
    pool = "the quick brown fox don't 0123 .,!? \n\t é東🙂 <|endoftext|>"
    for _ in range(200):
        text = "".join(rng.choice(pool) for _ in range(rng.randint(0, 120)))
        assert tok_native.encode(text) == tok_python.encode(text)


def test_encode_roundtrip(toy_pair):
    tok_native, _ = toy_pair
    text = "the lazy dog don't care about 123 café <|endoftext|> tail"
    assert tok_native.decode(tok_native.encode(text)) == text


def test_encode_array_matches_encode(toy_pair):
    tok_native, _ = toy_pair
    text = "the quick brown fox <|endoftext|> don't stop 123\n"
    assert tok_native.encode_array(text).tolist() == tok_native.encode(text)


def test_gpt2_fixture_parity(reference_fixtures):
    """Native path reproduces GPT-2 ids on the reference sample corpus."""
    from bpe_transformer_tpu.tokenization.gpt2 import (
        load_gpt2_merges,
        load_gpt2_vocab,
    )

    vocab = load_gpt2_vocab(reference_fixtures / "gpt2_vocab.json")
    merges = load_gpt2_merges(reference_fixtures / "gpt2_merges.txt")
    tok_native = BPETokenizer(dict(vocab), list(merges), ["<|endoftext|>"])
    tok_python = BPETokenizer(dict(vocab), list(merges), ["<|endoftext|>"])
    tok_python._native_tried = True
    sample = reference_fixtures / "tinystories_sample.txt"
    text = sample.read_text(encoding="utf-8")
    assert tok_native._native_encoder() is not None
    assert tok_native.encode(text) == tok_python.encode(text)


def test_memmap_fast_path_matches_stream_on_indented_text(toy_pair, tmp_path):
    """The array fast path must emit the same token stream as
    encode_iterable even when whitespace runs span newlines (indented
    lines), i.e. hosts with and without a C++ toolchain produce identical
    .bin files."""
    from bpe_transformer_tpu.data import tokenize_to_memmap

    tok_native, tok_python = toy_pair
    src = tmp_path / "corpus.txt"
    src.write_text("foo\n  bar\n\tbaz  \n   \n the quick qux" * 40)
    mm = tokenize_to_memmap(tok_native, src, tmp_path / "tokens.bin", dtype="uint32")
    with open(src, encoding="utf-8") as f:
        stream = list(tok_python.encode_iterable(f))
    assert mm.tolist() == stream


def test_pickled_tokenizer_rebuilds_native(toy_pair):
    """Pool workers receive a pickled tokenizer; the native handle must not
    travel through pickle but must rebuild lazily on the other side."""
    tok_native, _ = toy_pair
    clone = pickle.loads(pickle.dumps(tok_native))
    assert clone._native is None and clone._native_tried is False
    text = "the quick brown fox don't"
    assert clone.encode(text) == tok_native.encode(text)


# ----------------------------------------------------------- native trainer


def _python_trainer(vocab_size, specials, path):
    import os

    from bpe_transformer_tpu.tokenization import BPETrainer

    os.environ["BT_NATIVE"] = "0"
    try:
        t = BPETrainer(vocab_size=vocab_size, special_tokens=specials)
        t.train(path)
    finally:
        os.environ.pop("BT_NATIVE", None)
    return t


def _native_trainer(vocab_size, specials, path):
    from bpe_transformer_tpu.tokenization import BPETrainer

    t = BPETrainer(vocab_size=vocab_size, special_tokens=specials)
    # Call the native path directly and require that it actually ran — a
    # silent fallback would compare Python against Python.
    assert t._train_native_file(path) is True
    return t


@pytest.mark.parametrize("specials", [["<|endoftext|>"], []])
def test_trainer_native_matches_python(tmp_path, specials):
    corpus = tmp_path / "c.txt"
    corpus.write_text(
        (
            "the quick brown fox jumps over the lazy dog. don't stop! "
            "числа 123 café\n<|endoftext|>\nsecond doc  with   spaces\n"
        )
        * 120,
        encoding="utf-8",
    )
    tn = _native_trainer(420, specials, corpus)
    tp = _python_trainer(420, specials, corpus)
    assert tn.merges == tp.merges
    assert tn.vocab == tp.vocab


def test_trainer_native_matches_python_multichunk(tmp_path):
    """Corpus larger than one 4 MB read chunk: stream cuts must be lossless."""
    corpus = tmp_path / "big.txt"
    line = "a story about the fox.  it  has   whitespace runs \n"
    with open(corpus, "w", encoding="utf-8") as f:
        for i in range(90_000):
            f.write(line)
            if i % 97 == 0:
                f.write("<|endoftext|>")
    assert corpus.stat().st_size > (1 << 22)
    tn = _native_trainer(300, ["<|endoftext|>"], corpus)
    tp = _python_trainer(300, ["<|endoftext|>"], corpus)
    assert tn.merges == tp.merges


def test_trainer_native_leading_special_long_gap(tmp_path, monkeypatch):
    """A special token at index 0 of the pending buffer followed by a
    special-free run longer than the flush threshold must not leak the
    special's bytes into the pre-token counts (the add_prefix fallback
    path)."""
    from bpe_transformer_tpu.tokenization import trainer as trainer_mod

    monkeypatch.setattr(trainer_mod, "STREAM_CHUNK_CHARS", 64)
    monkeypatch.setattr(trainer_mod, "PENDING_FLUSH_CHARS", 256)
    corpus = tmp_path / "lead.txt"
    # Starts with the special, then >256 chars with no special at all.
    corpus.write_text(
        "<|endoftext|>" + "the quick brown fox story goes on. " * 40
        + "\n<|endoftext|>tail doc\n",
        encoding="utf-8",
    )
    tn = _native_trainer(300, ["<|endoftext|>"], corpus)
    tp = _python_trainer(300, ["<|endoftext|>"], corpus)
    assert tn.merges == tp.merges
    assert tn.vocab == tp.vocab


def test_counter_add_prefix_streaming_matches_single_shot():
    from bpe_transformer_tpu.native.engine import NativePretokenCounter

    text = ("word  runs \n\n tabs\tand don't 123 café " * 50).encode("utf-8")
    one = NativePretokenCounter()
    one.add(text)
    streamed = NativePretokenCounter()
    tail = b""
    for i in range(0, len(text), 97):  # awkward chunk size on purpose
        data = tail + text[i : i + 97]
        consumed = streamed.add_prefix(data)
        tail = data[consumed:]
    if tail:
        streamed.add(tail)
    assert sorted(one.items()) == sorted(streamed.items())


def test_reference_merge_snapshot_parity_native(reference_fixtures):
    """The native trainer reproduces the reference's pinned merge list."""
    ref_merges = reference_fixtures / "train-bpe-reference-merges.txt"
    if not ref_merges.exists():
        pytest.skip("reference merge fixture absent")
    from tests.test_train_bpe import _load_reference_merges  # reuse parser

    expected = _load_reference_merges(ref_merges)
    tn = _native_trainer(500, ["<|endoftext|>"], reference_fixtures / "corpus.en")
    assert tn.merges == expected


def test_counter_add_prefix_contraction_straddles_boundary():
    """A 3-char contraction split as b"we'l" | b"l go" must still count 'll."""
    from bpe_transformer_tpu.native.engine import NativePretokenCounter

    text = b"we'll go we'll go we'll go"
    one = NativePretokenCounter()
    one.add(text)
    for cut in range(1, len(text)):
        streamed = NativePretokenCounter()
        tail = b""
        for piece in (text[:cut], text[cut:]):
            data = tail + piece
            consumed = streamed.add_prefix(data)
            tail = data[consumed:]
        if tail:
            streamed.add(tail)
        assert sorted(one.items()) == sorted(streamed.items()), f"cut={cut}"


def test_trainer_native_matches_python_crlf(tmp_path):
    """CRLF corpora must not be newline-translated on the native path."""
    corpus = tmp_path / "crlf.txt"
    corpus.write_bytes(b"the cat\r\nsat on the mat\r\n" * 80)
    tn = _native_trainer(300, ["<|endoftext|>"], corpus)
    tp = _python_trainer(300, ["<|endoftext|>"], corpus)
    assert tn.merges == tp.merges


@pytest.mark.parametrize("training", [True, False])
@pytest.mark.parametrize("n_workers", [1, 2])
def test_count_pretokens_native_matches_python(tmp_path, training, n_workers):
    """The C++-scanner counting path (count_pretokens engine='native')
    produces byte-identical Counter contents to the Python regex path,
    serial and fanned out over processes, with and without special-token
    retention."""
    from bpe_transformer_tpu.native import is_available
    from bpe_transformer_tpu.tokenization.pretokenization import count_pretokens

    if not is_available():
        pytest.skip("native engine unavailable")

    corpus = tmp_path / "c.txt"
    corpus.write_text(
        ("hello world, it's 2026!\n  indented\ttabs\n<|endoftext|>"
         "héllo wörld \U0001f600 123\n") * 50,
        encoding="utf-8",
    )
    specials = ["<|endoftext|>"]
    py = count_pretokens(
        corpus, specials, training=training, n_workers=n_workers, engine="python"
    )
    nat = count_pretokens(
        corpus, specials, training=training, n_workers=n_workers, engine="native"
    )
    assert py == nat
