"""Multi-process jax.distributed bring-up, for real, on localhost CPU.

Round-2 verdict: ``initialize_distributed`` (parallel/mesh.py) had never
executed anywhere.  This launches an actual 2-process cluster (coordinator +
worker, 2 virtual CPU devices each), joins it through the package's own
bring-up helper, runs one explicit-collective dp train step over the
4-device GLOBAL mesh, and pins the cross-process loss against a
single-process oracle on an identical 4-device mesh.
"""

import dataclasses
import os
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

WORKER = Path(__file__).parent / "_distributed_worker.py"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _oracle_loss() -> float:
    """The same step on a single-process 4-device mesh (this test process
    runs under the conftest's 8-virtual-device env; use the first 4)."""
    import jax
    import jax.numpy as jnp

    from bpe_transformer_tpu.models import TS_TEST_CONFIG, init_params
    from bpe_transformer_tpu.optim import adamw_init
    from bpe_transformer_tpu.parallel import (
        make_dp_train_step,
        make_mesh,
        shard_batch,
    )
    from bpe_transformer_tpu.training.train_step import TrainHParams

    config = dataclasses.replace(TS_TEST_CONFIG, vocab_size=512, context_length=32)
    hparams = TrainHParams(warmup_iters=2, cosine_cycle_iters=10)
    params = init_params(jax.random.PRNGKey(0), config)
    opt_state = adamw_init(params)
    rng = np.random.default_rng(0)
    x = rng.integers(0, config.vocab_size, size=(8, 32), dtype=np.int32)
    y = rng.integers(0, config.vocab_size, size=(8, 32), dtype=np.int32)

    mesh = make_mesh({"data": 4}, devices=jax.devices()[:4])
    step = make_dp_train_step(config, hparams, mesh)
    xb, yb = shard_batch((jnp.asarray(x), jnp.asarray(y)), mesh)
    _, _, metrics = step(params, opt_state, xb, yb)
    return float(metrics["loss"])


@pytest.mark.slow
def test_two_process_distributed_dp_step():
    # Bounded by the communicate(timeout=240) below, not a pytest plugin.
    port = _free_port()
    coordinator = f"127.0.0.1:{port}"
    env = dict(os.environ)
    # The worker sets its own JAX_PLATFORMS/XLA_FLAGS before importing jax.
    procs = [
        subprocess.Popen(
            [sys.executable, str(WORKER), coordinator, "2", str(pid)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            env=env,
            text=True,
        )
        for pid in (0, 1)
    ]
    # Drain both pipes CONCURRENTLY: the workers block on each other in the
    # collective, so a sequential communicate() could deadlock on a full
    # pipe buffer if one worker logs verbosely.
    from concurrent.futures import ThreadPoolExecutor

    def drain(p):
        out, err = p.communicate(timeout=240)
        return p.returncode, out, err

    try:
        with ThreadPoolExecutor(max_workers=2) as pool:
            outs = list(pool.map(drain, procs))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("distributed processes hung")

    for rc, out, err in outs:
        if rc != 0 and "Multiprocess computations aren't implemented" in err:
            # This jax build's CPU backend cannot execute cross-process
            # collectives at all (jax 0.4.x limitation) — the bring-up path
            # under test is a TPU-pod feature; nothing here can be fixed.
            pytest.skip(
                "CPU backend of this jax build does not implement "
                "multiprocess computations"
            )
        assert rc == 0, f"worker failed rc={rc}\nstdout:{out}\nstderr:{err[-2000:]}"

    dist_loss = None
    for rc, out, err in outs:
        for line in out.splitlines():
            if line.startswith("DIST_LOSS"):
                dist_loss = float(line.split()[1])
    assert dist_loss is not None, outs
    np.testing.assert_allclose(dist_loss, _oracle_loss(), rtol=1e-5)
