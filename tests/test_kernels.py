"""Pallas kernels (interpret mode on CPU) + ring attention parity."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bpe_transformer_tpu.kernels.pallas.flash_attention import (
    _xla_attention,
    _xla_rope_attention,
    flash_attention,
    flash_attention_with_rope,
)
from bpe_transformer_tpu.kernels.pallas.gelu import gelu, gelu_reference
from bpe_transformer_tpu.parallel import make_mesh
from bpe_transformer_tpu.parallel.ring_attention import make_ring_attention


# ------------------------------------------------------------------- gelu


@pytest.mark.parametrize("shape", [(7,), (33, 17), (2, 3, 130)])
def test_gelu_matches_reference_formula(shape):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal(shape).astype(np.float32) * 3)
    out = gelu(x)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(gelu_reference(x)), atol=1e-6
    )


@pytest.mark.slow
def test_gelu_matches_torch_tanh_gelu():
    import torch
    import torch.nn.functional as F

    x = np.linspace(-5, 5, 257, dtype=np.float32)
    ours = np.asarray(gelu(jnp.asarray(x)))
    theirs = F.gelu(torch.from_numpy(x), approximate="tanh").numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-5)


# -------------------------------------------------------- flash attention


@pytest.mark.parametrize(
    "batch,heads,seq,d,causal",
    [
        (2, 2, 128, 64, True),
        (1, 4, 256, 64, True),
        (2, 2, 128, 64, False),
        (1, 1, 200, 32, True),  # seq not divisible by block, odd head dim
    ],
)
def test_flash_attention_matches_xla(batch, heads, seq, d, causal):
    rng = np.random.default_rng(1)
    mk = lambda: jnp.asarray(
        rng.standard_normal((batch, heads, seq, d)).astype(np.float32)
    )
    q, k, v = mk(), mk(), mk()
    out = flash_attention(q, k, v, causal, 128, 128, True)
    expected = _xla_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_flash_attention_gradients_match_xla():
    rng = np.random.default_rng(2)
    shape = (1, 2, 128, 32)
    q, k, v = (
        jnp.asarray(rng.standard_normal(shape).astype(np.float32)) for _ in range(3)
    )

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, True, 128, 128, True).sum()

    def loss_xla(q, k, v):
        return _xla_attention(q, k, v, True).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_xla = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_xla):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


@pytest.mark.parametrize(
    "shape,block_q,block_k,causal",
    [
        ((1, 2, 200, 32), 64, 32, True),   # ragged seq, unequal blocks
        ((2, 1, 96, 16), 32, 96, True),    # block_k > block_q, lcm padding
        ((1, 1, 128, 64), 64, 64, False),  # non-causal backward
    ],
)
@pytest.mark.slow
def test_flash_backward_blockwise_parity(shape, block_q, block_k, causal):
    """The FA-2 Pallas backward (dQ/dK/dV kernels, no S^2 materialization)
    matches the materialized-scores XLA vjp across padding/blocking shapes."""
    rng = np.random.default_rng(11)
    q, k, v = (
        jnp.asarray(rng.standard_normal(shape).astype(np.float32)) for _ in range(3)
    )
    ct = jnp.asarray(rng.standard_normal(shape).astype(np.float32))

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal, block_q, block_k, True) * ct).sum()

    def loss_xla(q, k, v):
        return (_xla_attention(q, k, v, causal) * ct).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_xla = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_xla):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5)


def test_flash_backward_bf16_grad_dtype():
    rng = np.random.default_rng(12)
    shape = (1, 2, 128, 64)
    q, k, v = (
        jnp.asarray(rng.standard_normal(shape), dtype=jnp.bfloat16)
        for _ in range(3)
    )
    grads = jax.grad(
        lambda q, k, v: flash_attention(q, k, v, True, 128, 128, True)
        .astype(jnp.float32)
        .sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    f32 = lambda t: tuple(np.asarray(x, dtype=np.float32) for x in t)
    expected = jax.grad(
        lambda q, k, v: _xla_attention(q, k, v, True).astype(jnp.float32).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(f32(grads), f32(expected)):
        assert a.dtype == np.float32
        np.testing.assert_allclose(a, b, atol=5e-2)
    for g in grads:
        assert g.dtype == jnp.bfloat16


@pytest.mark.slow
def test_fused_rope_table_gradients_match_xla():
    """cos/sin table grads of the fused kernel's vjp match the XLA oracle
    (tables are non-trainable in the model, but the vjp stays honest)."""
    from bpe_transformer_tpu.ops.rope import rope_tables

    rng = np.random.default_rng(13)
    shape = (1, 2, 64, 32)
    q, k, v = (
        jnp.asarray(rng.standard_normal(shape).astype(np.float32)) for _ in range(3)
    )
    cos, sin = rope_tables(shape[-1], shape[-2])

    g_fused = jax.grad(
        lambda c, s: flash_attention_with_rope(q, k, v, c, s, True, 32, 32, True).sum(),
        argnums=(0, 1),
    )(cos, sin)
    g_xla = jax.grad(
        lambda c, s: _xla_rope_attention(q, k, v, c, s, True).sum(),
        argnums=(0, 1),
    )(cos, sin)
    for a, b in zip(g_fused, g_xla):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5)


def test_flash_attention_bf16():
    rng = np.random.default_rng(3)
    shape = (1, 2, 128, 64)
    q, k, v = (
        jnp.asarray(rng.standard_normal(shape), dtype=jnp.bfloat16)
        for _ in range(3)
    )
    out = flash_attention(q, k, v, True, 128, 128, True)
    expected = _xla_attention(q, k, v, True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32),
        np.asarray(expected, dtype=np.float32),
        atol=3e-2,
    )


# ------------------------------------------------ fused RoPE + attention


@pytest.mark.parametrize(
    "batch,heads,seq,d",
    [
        (2, 4, 48, 64),   # seq not divisible by block
        (1, 2, 128, 32),
        (1, 1, 200, 16),  # small head dim, ragged seq
    ],
)
def test_fused_rope_flash_attention_matches_xla(batch, heads, seq, d):
    from bpe_transformer_tpu.ops.rope import rope_tables

    rng = np.random.default_rng(6)
    mk = lambda: jnp.asarray(
        rng.standard_normal((batch, heads, seq, d)).astype(np.float32)
    )
    q, k, v = mk(), mk(), mk()
    cos, sin = rope_tables(d, seq)
    out = flash_attention_with_rope(q, k, v, cos, sin, True, 32, 16, True)
    expected = _xla_rope_attention(q, k, v, cos, sin, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


@pytest.mark.slow
def test_fused_rope_flash_attention_gradients_match_xla():
    from bpe_transformer_tpu.ops.rope import rope_tables

    rng = np.random.default_rng(7)
    shape = (1, 2, 96, 32)
    q, k, v = (
        jnp.asarray(rng.standard_normal(shape).astype(np.float32)) for _ in range(3)
    )
    cos, sin = rope_tables(shape[-1], shape[-2])

    def loss_fused(q, k, v):
        return flash_attention_with_rope(q, k, v, cos, sin, True, 32, 32, True).sum()

    def loss_xla(q, k, v):
        return _xla_rope_attention(q, k, v, cos, sin, True).sum()

    g_fused = jax.grad(loss_fused, argnums=(0, 1, 2))(q, k, v)
    g_xla = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_fused, g_xla):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


@pytest.mark.slow
def test_model_fused_flash_attention_matches_xla_impl():
    import dataclasses

    from bpe_transformer_tpu.models import TS_TEST_CONFIG, forward, init_params

    cfg = dataclasses.replace(TS_TEST_CONFIG, vocab_size=512)
    params = init_params(jax.random.PRNGKey(0), cfg)
    ids = jnp.asarray(np.random.default_rng(8).integers(0, 512, size=(2, 16)))
    base = forward(params, ids, cfg)
    # min_seq=0 forces the FUSED kernel even at this tiny seq (the default
    # crossover would auto-fall-back to plain flash below 2048).
    fused_cfg = dataclasses.replace(
        cfg, attention_impl="flash_fused", flash_fused_min_seq=0
    )
    fused = forward(params, ids, fused_cfg)
    np.testing.assert_allclose(
        np.asarray(base), np.asarray(fused), atol=2e-4, rtol=1e-3
    )


@pytest.mark.slow  # 870s tier-1 budget (PR 11 sweep; ISSUE 11 tooling guard) — runs in the full matrix
def test_flash_fused_crossover_dispatch(monkeypatch):
    """Below flash_fused_min_seq the model must run the PLAIN flash kernel
    (RoPE outside) — the fused kernel loses at short seq on-chip (r2 bench:
    2.330 vs 2.168 ms at 1k) — and must call the fused kernel at/above the
    threshold."""
    import dataclasses
    import importlib

    # `pallas/__init__` re-exports a FUNCTION named flash_attention that
    # shadows the submodule on `import ... as` attribute resolution; go
    # through importlib to get the actual module.
    fa = importlib.import_module(
        "bpe_transformer_tpu.kernels.pallas.flash_attention"
    )
    from bpe_transformer_tpu.models import TS_TEST_CONFIG, forward, init_params

    cfg = dataclasses.replace(
        TS_TEST_CONFIG, vocab_size=512, attention_impl="flash_fused"
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    ids = jnp.asarray(np.random.default_rng(9).integers(0, 512, size=(2, 16)))

    calls = []
    real = fa.flash_attention_with_rope
    monkeypatch.setattr(
        fa,
        "flash_attention_with_rope",
        lambda *a, **k: calls.append(1) or real(*a, **k),
    )
    forward(params, ids, cfg)  # seq 16 < 2048: plain-flash fallback
    assert not calls, "fused kernel invoked below the crossover"

    forced = dataclasses.replace(cfg, flash_fused_min_seq=0)
    forward(params, ids, forced)
    assert calls, "fused kernel not invoked when forced"


# ---------------------------------------------------------- ring attention


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.slow
def test_ring_attention_matches_full(causal):
    mesh = make_mesh({"data": 8})
    rng = np.random.default_rng(4)
    shape = (2, 2, 8 * 16, 32)  # seq 128 split 8 ways -> 16 per device
    q, k, v = (
        jnp.asarray(rng.standard_normal(shape).astype(np.float32)) for _ in range(3)
    )
    ring = make_ring_attention(mesh, "data", causal)
    out = ring(q, k, v)
    expected = _xla_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


@pytest.mark.slow
def test_ring_attention_gradients_flow():
    mesh = make_mesh({"data": 8})
    rng = np.random.default_rng(5)
    shape = (1, 2, 64, 16)
    q, k, v = (
        jnp.asarray(rng.standard_normal(shape).astype(np.float32)) for _ in range(3)
    )
    ring = make_ring_attention(mesh, "data", True)

    g_ring = jax.grad(lambda q_: ring(q_, k, v).sum())(q)
    g_full = jax.grad(lambda q_: _xla_attention(q_, k, v, True).sum())(q)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_full), atol=2e-5)


# ------------------------------------------------- model kernel integration


def test_model_flash_attention_matches_xla_impl():
    import dataclasses

    from bpe_transformer_tpu.models import TS_TEST_CONFIG, forward, init_params

    cfg = dataclasses.replace(TS_TEST_CONFIG, vocab_size=512)
    params = init_params(jax.random.PRNGKey(0), cfg)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 512, size=(2, 16)))
    base = forward(params, ids, cfg)
    flash_cfg = dataclasses.replace(cfg, attention_impl="flash")
    flashed = forward(params, ids, flash_cfg)
    np.testing.assert_allclose(
        np.asarray(base), np.asarray(flashed), atol=2e-4, rtol=1e-3
    )


def test_model_gelu_ffn_trains():
    import dataclasses

    from bpe_transformer_tpu.models import TS_TEST_CONFIG, init_params
    from bpe_transformer_tpu.training import TrainHParams, make_train_step
    from bpe_transformer_tpu.optim import adamw_init

    cfg = dataclasses.replace(TS_TEST_CONFIG, vocab_size=512, ffn_type="gelu")
    params = init_params(jax.random.PRNGKey(0), cfg)
    step = make_train_step(cfg, TrainHParams(warmup_iters=1, cosine_cycle_iters=5))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 512, size=(4, 16)))
    y = jnp.asarray(rng.integers(0, 512, size=(4, 16)))
    params, _, metrics = step(params, adamw_init(params), x, y)
    assert np.isfinite(float(metrics["loss"]))
    assert np.isfinite(float(metrics["grad_norm"]))


def test_gelu_large_inputs_finite():
    """exp-based tanh must not overflow: gelu(11) == 11, not NaN."""
    x = jnp.asarray([11.0, 50.0, 1000.0, -1000.0], dtype=jnp.float32)
    out = np.asarray(gelu(x))
    assert np.isfinite(out).all()
    np.testing.assert_allclose(out[:3], np.asarray(x[:3]), rtol=1e-6)
    assert out[3] == 0.0


def test_flash_attention_asymmetric_blocks():
    """seq not divisible by block_q alone must still produce every row."""
    rng = np.random.default_rng(7)
    shape = (1, 2, 100, 32)
    q, k, v = (
        jnp.asarray(rng.standard_normal(shape).astype(np.float32)) for _ in range(3)
    )
    out = flash_attention(q, k, v, True, 64, 256, True)
    expected = _xla_attention(q, k, v, True)
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


# --------------------------------------------------------------- fused swiglu


def test_fused_swiglu_matches_xla():
    from bpe_transformer_tpu.kernels.pallas.swiglu import swiglu_fused
    from bpe_transformer_tpu.ops.core import swiglu

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 24, 64)).astype(np.float32))
    w1 = jnp.asarray(rng.normal(size=(128, 64)).astype(np.float32) * 0.05)
    w2 = jnp.asarray(rng.normal(size=(64, 128)).astype(np.float32) * 0.05)
    w3 = jnp.asarray(rng.normal(size=(128, 64)).astype(np.float32) * 0.05)

    got = swiglu_fused(x, w1, w2, w3, 16, 32, True)
    want = swiglu(x, w1, w2, w3)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_fused_swiglu_gradients_match_xla():
    import jax

    from bpe_transformer_tpu.kernels.pallas.swiglu import swiglu_fused
    from bpe_transformer_tpu.ops.core import swiglu

    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(8, 32)).astype(np.float32))
    w1 = jnp.asarray(rng.normal(size=(64, 32)).astype(np.float32) * 0.05)
    w2 = jnp.asarray(rng.normal(size=(32, 64)).astype(np.float32) * 0.05)
    w3 = jnp.asarray(rng.normal(size=(64, 32)).astype(np.float32) * 0.05)

    loss_fused = lambda *a: swiglu_fused(*a, 8, 16, True).sum()
    loss_xla = lambda *a: swiglu(*a).sum()
    g_fused = jax.grad(loss_fused, argnums=(0, 1, 2, 3))(x, w1, w2, w3)
    g_xla = jax.grad(loss_xla, argnums=(0, 1, 2, 3))(x, w1, w2, w3)
    for a, b in zip(g_fused, g_xla):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_model_fused_swiglu_matches_xla_impl():
    import dataclasses

    import jax

    from bpe_transformer_tpu.models import TS_TEST_CONFIG, forward, init_params

    cfg_xla = dataclasses.replace(TS_TEST_CONFIG, vocab_size=256)
    cfg_pallas = dataclasses.replace(cfg_xla, ffn_impl="pallas")
    params = init_params(jax.random.PRNGKey(0), cfg_xla)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 256, size=(2, cfg_xla.context_length)))
    a = forward(params, ids, cfg_xla)
    b = forward(params, ids, cfg_pallas)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


# ------------------------------------------------------ decode attention


@pytest.mark.parametrize(
    "batch,heads,kv_heads,ctx,d,pos",
    [
        (2, 4, 4, 128, 64, 100),   # MHA
        (2, 8, 2, 256, 64, 0),     # GQA, frontier at the first position
        (1, 4, 1, 200, 48, 199),   # MQA, ragged ctx + odd head dim, full cache
        (3, 6, 3, 512, 64, 17),    # frontier inside the first block
    ],
)
def test_decode_attention_matches_xla(batch, heads, kv_heads, ctx, d, pos):
    from bpe_transformer_tpu.kernels.pallas.decode_attention import (
        decode_attention,
        xla_decode_attention,
    )

    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.standard_normal((batch, heads, d)).astype(np.float32))
    mk = lambda: jnp.asarray(
        rng.standard_normal((batch, kv_heads, ctx, d)).astype(np.float32)
    )
    k, v = mk(), mk()
    out = decode_attention(q, k, v, pos, interpret=True)
    ref = xla_decode_attention(q, k, v, pos)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_decode_attention_traced_pos_single_compile():
    """pos rides scalar prefetch: one jitted program serves every frontier
    (the generation loop's lax.scan carries pos as a traced value)."""
    from bpe_transformer_tpu.kernels.pallas.decode_attention import (
        decode_attention,
        xla_decode_attention,
    )

    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.standard_normal((2, 8, 64)).astype(np.float32))
    mk = lambda: jnp.asarray(
        rng.standard_normal((2, 4, 256, 64)).astype(np.float32)
    )
    k, v = mk(), mk()
    f = jax.jit(lambda q, k, v, p: decode_attention(q, k, v, p, interpret=True))
    for pos in (0, 100, 255):
        np.testing.assert_allclose(
            np.asarray(f(q, k, v, jnp.int32(pos))),
            np.asarray(xla_decode_attention(q, k, v, pos)),
            atol=2e-5,
            err_msg=f"pos {pos}",
        )


def test_decode_attention_bf16():
    """bf16 cache/queries (the decode perf path): f32 accumulation inside,
    output close to the f32 oracle at bf16 tolerance."""
    from bpe_transformer_tpu.kernels.pallas.decode_attention import (
        decode_attention,
        xla_decode_attention,
    )

    rng = np.random.default_rng(4)
    q32 = rng.standard_normal((2, 4, 64)).astype(np.float32)
    k32 = rng.standard_normal((2, 4, 128, 64)).astype(np.float32)
    v32 = rng.standard_normal((2, 4, 128, 64)).astype(np.float32)
    out = decode_attention(
        jnp.asarray(q32, jnp.bfloat16),
        jnp.asarray(k32, jnp.bfloat16),
        jnp.asarray(v32, jnp.bfloat16),
        64,
        interpret=True,
    )
    assert out.dtype == jnp.bfloat16
    ref = xla_decode_attention(
        jnp.asarray(q32), jnp.asarray(k32), jnp.asarray(v32), 64
    )
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), atol=3e-2
    )


def test_decode_attention_rejects_bad_shapes():
    from bpe_transformer_tpu.kernels.pallas.decode_attention import (
        decode_attention,
    )

    q = jnp.zeros((2, 5, 64))
    kv = jnp.zeros((2, 2, 128, 64))
    with pytest.raises(ValueError, match="not divisible"):
        decode_attention(q, kv, kv, 0, interpret=True)
    with pytest.raises(ValueError, match="shape mismatch"):
        decode_attention(jnp.zeros((2, 4, 32)), kv, kv, 0, interpret=True)


def test_decode_attention_gpt2_shape():
    """The queued device cell's geometry (gpt2-small: H=12, d_head=64,
    ctx=1024, bf16): parity at several causal frontiers, one jitted program."""
    from bpe_transformer_tpu.kernels.pallas.decode_attention import (
        decode_attention,
        xla_decode_attention,
    )

    rng = np.random.default_rng(9)
    q = jnp.asarray(rng.standard_normal((1, 12, 64)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((1, 12, 1024, 64)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((1, 12, 1024, 64)), jnp.bfloat16)
    f = jax.jit(lambda q, k, v, p: decode_attention(q, k, v, p, interpret=True))
    for pos in (63, 512, 1023):
        out = f(q, k, v, jnp.int32(pos))
        ref = xla_decode_attention(q, k, v, pos)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            atol=3e-2, err_msg=f"pos {pos}",
        )


# ------------------------------------------------ paged-native flash decode


def _paged_pool(rng, num_blocks, kv_heads, block_size, d, dtype=np.float32):
    return jnp.asarray(
        rng.standard_normal((num_blocks, kv_heads, block_size, d)).astype(
            dtype
        )
    )


@pytest.mark.parametrize(
    "slots,heads,kv_heads,block_size,nbs,d",
    [
        (3, 8, 4, 8, 4, 16),    # GQA, the serving test shape
        (2, 4, 4, 16, 4, 64),   # MHA, production-ish block
        (1, 6, 1, 8, 8, 48),    # MQA, deep chain + odd head dim
    ],
)
def test_paged_decode_attention_matches_gathered_xla(
    slots, heads, kv_heads, block_size, nbs, d
):
    """The paged-NATIVE kernel (block table consumed in the index maps)
    equals the gather-then-attend reference at ragged per-slot frontiers
    — including slots parked on the trash block (inactive)."""
    from bpe_transformer_tpu.kernels.pallas.decode_attention import (
        paged_decode_attention,
        xla_decode_attention,
    )
    from bpe_transformer_tpu.models.decode import gather_paged_kv

    rng = np.random.default_rng(7)
    num_blocks = slots * nbs + 1
    k_pool = _paged_pool(rng, num_blocks, kv_heads, block_size, d)
    v_pool = _paged_pool(rng, num_blocks, kv_heads, block_size, d)
    # Distinct non-trash blocks per slot, deliberately shuffled: the
    # kernel must follow the table, not pool order.
    perm = rng.permutation(np.arange(1, num_blocks))
    tables = jnp.asarray(perm.reshape(slots, nbs), jnp.int32)
    ctx = nbs * block_size
    pos = jnp.asarray(
        [0, ctx - 1, ctx // 2][:slots] + [3] * max(0, slots - 3), jnp.int32
    )[:slots]
    q = jnp.asarray(rng.standard_normal((slots, heads, d)).astype(np.float32))

    out = paged_decode_attention(q, k_pool, v_pool, tables, pos,
                                 interpret=True)
    ref = xla_decode_attention(
        q, gather_paged_kv(k_pool, tables), gather_paged_kv(v_pool, tables),
        pos,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_paged_decode_attention_int8_matches_dequant_reference():
    """int8 blocks + per-block-per-head scales: the kernel's in-register
    dequant equals attention over the explicitly dequantized gathered
    cache (same numbers, no transient)."""
    from bpe_transformer_tpu.kernels.pallas.decode_attention import (
        paged_decode_attention,
        xla_decode_attention,
    )
    from bpe_transformer_tpu.models.decode import gather_paged_kv

    rng = np.random.default_rng(11)
    slots, heads, kv_heads, block_size, nbs, d = 2, 8, 4, 8, 4, 16
    num_blocks = slots * nbs + 1
    kf = _paged_pool(rng, num_blocks, kv_heads, block_size, d)
    vf = _paged_pool(rng, num_blocks, kv_heads, block_size, d)
    k_scale = jnp.asarray(
        (np.abs(rng.standard_normal((num_blocks, kv_heads))) / 40 + 0.01)
        .astype(np.float32)
    )
    v_scale = jnp.asarray(
        (np.abs(rng.standard_normal((num_blocks, kv_heads))) / 40 + 0.01)
        .astype(np.float32)
    )
    kq = jnp.clip(
        jnp.round(kf / k_scale[:, :, None, None]), -127, 127
    ).astype(jnp.int8)
    vq = jnp.clip(
        jnp.round(vf / v_scale[:, :, None, None]), -127, 127
    ).astype(jnp.int8)
    tables = jnp.asarray(
        rng.permutation(np.arange(1, num_blocks)).reshape(slots, nbs),
        jnp.int32,
    )
    pos = jnp.asarray([9, 31], jnp.int32)
    q = jnp.asarray(rng.standard_normal((slots, heads, d)).astype(np.float32))

    out = paged_decode_attention(
        q, kq, vq, tables, pos, k_scale=k_scale, v_scale=v_scale,
        interpret=True,
    )
    kd = kq.astype(jnp.float32) * k_scale[:, :, None, None]
    vd = vq.astype(jnp.float32) * v_scale[:, :, None, None]
    ref = xla_decode_attention(
        q, gather_paged_kv(kd, tables), gather_paged_kv(vd, tables), pos
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_paged_decode_attention_single_compile_across_state():
    """tables/pos ride scalar prefetch: one jitted program serves every
    table layout and frontier (the paged tick's bounded-compile
    contract)."""
    from bpe_transformer_tpu.kernels.pallas.decode_attention import (
        paged_decode_attention,
        xla_decode_attention,
    )
    from bpe_transformer_tpu.models.decode import gather_paged_kv

    rng = np.random.default_rng(3)
    slots, heads, kv_heads, block_size, nbs, d = 2, 4, 2, 8, 4, 16
    num_blocks = slots * nbs + 1
    k_pool = _paged_pool(rng, num_blocks, kv_heads, block_size, d)
    v_pool = _paged_pool(rng, num_blocks, kv_heads, block_size, d)
    f = jax.jit(
        lambda q, k, v, t, p: paged_decode_attention(
            q, k, v, t, p, interpret=True
        )
    )
    q = jnp.asarray(rng.standard_normal((slots, heads, d)).astype(np.float32))
    for seed in (0, 1, 2):
        r2 = np.random.default_rng(seed)
        tables = jnp.asarray(
            r2.permutation(np.arange(1, num_blocks)).reshape(slots, nbs),
            jnp.int32,
        )
        pos = jnp.asarray(r2.integers(0, nbs * block_size, slots), jnp.int32)
        out = f(q, k_pool, v_pool, tables, pos)
        ref = xla_decode_attention(
            q, gather_paged_kv(k_pool, tables),
            gather_paged_kv(v_pool, tables), pos,
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)
    assert f._cache_size() == 1


def test_paged_decode_attention_rejects_bad_shapes():
    from bpe_transformer_tpu.kernels.pallas.decode_attention import (
        paged_decode_attention,
    )

    q = jnp.zeros((2, 4, 16))
    pool = jnp.zeros((9, 2, 8, 16))
    tables = jnp.zeros((2, 4), jnp.int32)
    pos = jnp.zeros((2,), jnp.int32)
    with pytest.raises(ValueError, match="tables"):
        paged_decode_attention(q, pool, pool, jnp.zeros((3, 4), jnp.int32),
                               pos, interpret=True)
    with pytest.raises(ValueError, match="shape mismatch"):
        paged_decode_attention(q, pool, jnp.zeros((9, 2, 8, 8)), tables,
                               pos, interpret=True)
    with pytest.raises(ValueError, match="int8"):
        paged_decode_attention(q, pool, pool, tables, pos,
                               k_scale=jnp.zeros((9, 2)), interpret=True)
    with pytest.raises(ValueError, match="not divisible"):
        paged_decode_attention(jnp.zeros((2, 5, 16)), pool, pool, tables,
                               pos, interpret=True)
