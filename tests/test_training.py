"""End-to-end training loop: loss decreases, resume is exact, CLI drives it."""

import dataclasses
import json

import numpy as np
import pytest

from bpe_transformer_tpu.models import ModelConfig
from bpe_transformer_tpu.training import LoopConfig, TrainHParams, train
from bpe_transformer_tpu.training.cli import main as cli_main

TINY = ModelConfig(
    vocab_size=256,
    context_length=32,
    d_model=64,
    num_layers=2,
    num_heads=4,
    d_ff=128,
)
HP = TrainHParams(
    max_learning_rate=1e-3,
    min_learning_rate=1e-4,
    warmup_iters=5,
    cosine_cycle_iters=60,
)


@pytest.fixture(scope="module")
def byte_data():
    """A byte-level corpus with obvious structure the tiny LM can learn."""
    rng = np.random.default_rng(0)
    text = b"hello world. " * 4000
    return np.frombuffer(text, dtype=np.uint8).astype(np.uint16)


def test_loss_decreases(byte_data, tmp_path):
    loop = LoopConfig(
        steps=60,
        batch_size=16,
        log_every=10,
        eval_every=30,
        eval_batches=2,
        checkpoint_every=60,
        checkpoint_dir=str(tmp_path / "ckpt"),
    )
    summary = train(TINY, HP, loop, byte_data, byte_data, log_fn=lambda *_: None)
    first = summary["history"][0]["loss"]
    last = summary["final_train_loss"]
    assert last < first * 0.7, (first, last)
    assert np.isfinite(summary["final_val_loss"])
    assert (tmp_path / "ckpt" / "latest.ckpt").exists()
    assert (tmp_path / "ckpt" / "summary.json").exists()


def test_resume_continues(byte_data, tmp_path):
    ckpt_dir = tmp_path / "ckpt"
    loop_a = LoopConfig(
        steps=10, batch_size=8, log_every=5, checkpoint_every=10,
        checkpoint_dir=str(ckpt_dir),
    )
    train(TINY, HP, loop_a, byte_data, log_fn=lambda *_: None)

    loop_b = dataclasses.replace(loop_a, steps=20)
    summary = train(
        TINY, HP, loop_b, byte_data,
        resume_from=ckpt_dir / "latest.ckpt", log_fn=lambda *_: None,
    )
    assert summary["history"][-1]["step"] == 20


def test_dp_training_runs(byte_data):
    loop = LoopConfig(
        steps=8, batch_size=16, log_every=4, parallel="dp", mesh_axes={"data": 8}
    )
    summary = train(TINY, HP, loop, byte_data, log_fn=lambda *_: None)
    assert np.isfinite(summary["final_train_loss"])


@pytest.mark.slow
def test_cli_end_to_end(tmp_path, tiny_corpus, capsys):
    """The full user journey: train-tokenizer -> tokenize -> train -> eval ->
    generate, all through the CLI."""
    tok_dir = tmp_path / "tok"
    assert (
        cli_main(
            [
                "train-tokenizer",
                "--input", str(tiny_corpus),
                "--vocab-size", "300",
                "--output-dir", str(tok_dir),
            ]
        )
        == 0
    )
    tokens_path = tmp_path / "tokens.bin"
    assert (
        cli_main(
            [
                "tokenize",
                "--input", str(tiny_corpus),
                "--tokenizer-dir", str(tok_dir),
                "--output", str(tokens_path),
            ]
        )
        == 0
    )
    cfg_path = tmp_path / "model.json"
    dataclasses.replace(TINY, vocab_size=300).to_json(cfg_path)
    ckpt_dir = tmp_path / "ckpt"
    assert (
        cli_main(
            [
                "train",
                "--data", str(tokens_path),
                "--val-data", str(tokens_path),
                "--model-config", str(cfg_path),
                "--steps", "12",
                "--batch-size", "8",
                "--log-every", "6",
                "--eval-every", "12",
                "--checkpoint-every", "12",
                "--checkpoint-dir", str(ckpt_dir),
                "--warmup", "2",
            ]
        )
        == 0
    )
    out = capsys.readouterr().out
    summary = json.loads(out.strip().splitlines()[-1])
    assert np.isfinite(summary["final_train_loss"])

    assert (
        cli_main(
            [
                "eval",
                "--checkpoint", str(ckpt_dir / "latest.ckpt"),
                "--data", str(tokens_path),
                "--model-config", str(cfg_path),
                "--batches", "2",
                "--batch-size", "4",
            ]
        )
        == 0
    )
    eval_out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert np.isfinite(eval_out["val_loss"])

    assert (
        cli_main(
            [
                "generate",
                "--checkpoint", str(ckpt_dir / "latest.ckpt"),
                "--tokenizer-dir", str(tok_dir),
                "--model-config", str(cfg_path),
                "--prompt", "the quick",
                "--max-new-tokens", "8",
                "--temperature", "0.8",
            ]
        )
        == 0
    )
    gen_out = capsys.readouterr().out
    assert gen_out.startswith("the quick")

    # Self-describing checkpoints: eval and generate recover the stored
    # architecture when neither --preset nor --model-config is given (a
    # defaulted preset that mismatches the weights used to crash deep in
    # RoPE with an opaque shape error).
    assert (
        cli_main(
            [
                "eval",
                "--checkpoint", str(ckpt_dir / "latest.ckpt"),
                "--data", str(tokens_path),
                "--batches", "1",
                "--batch-size", "4",
            ]
        )
        == 0
    )
    stored_eval = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert np.isfinite(stored_eval["val_loss"])
    assert (
        cli_main(
            [
                "generate",
                "--checkpoint", str(ckpt_dir / "latest.ckpt"),
                "--tokenizer-dir", str(tok_dir),
                "--prompt", "the quick",
                "--max-new-tokens", "4",
            ]
        )
        == 0
    )
    assert capsys.readouterr().out.startswith("the quick")

    # --decode-attention pallas: the flash-decoding kernel through the CLI,
    # greedy so the text must equal the default xla path's exactly.
    def greedy(*extra):
        assert (
            cli_main(
                [
                    "generate",
                    "--checkpoint", str(ckpt_dir / "latest.ckpt"),
                    "--tokenizer-dir", str(tok_dir),
                    "--prompt", "the quick",
                    "--max-new-tokens", "6",
                    "--temperature", "0.0",
                    *extra,
                ]
            )
            == 0
        )
        return capsys.readouterr().out
    assert greedy("--decode-attention", "pallas") == greedy()


def test_generate_greedy_and_topk(byte_data):
    import jax

    from bpe_transformer_tpu.models import init_params
    from bpe_transformer_tpu.training import generate_ids

    params = init_params(jax.random.PRNGKey(0), TINY)
    greedy_a = generate_ids(params, TINY, [1, 2, 3], 5, temperature=0.0)
    greedy_b = generate_ids(params, TINY, [1, 2, 3], 5, temperature=0.0)
    assert greedy_a == greedy_b
    sampled = generate_ids(params, TINY, [1, 2, 3], 5, temperature=1.0, top_k=5, seed=1)
    assert len(sampled) == 5
    assert all(0 <= t < TINY.vocab_size for t in sampled)


@pytest.mark.slow
def test_pp_training_runs(byte_data, tmp_path):
    """GPipe pipeline loop: 2 stages x 4-way data parallel, with eval +
    checkpoint in the stacked-stage layout."""
    loop = LoopConfig(
        steps=8,
        batch_size=16,
        log_every=4,
        eval_every=8,
        checkpoint_every=8,
        checkpoint_dir=str(tmp_path / "ckpt"),
        parallel="pp",
        mesh_axes={"data": 4, "pp": 2},
        pp_microbatches=2,
    )
    summary = train(TINY, HP, loop, byte_data, val_data=byte_data, log_fn=lambda *_: None)
    assert np.isfinite(summary["final_train_loss"])
    assert np.isfinite(summary["final_val_loss"])


def test_moe_training_runs(byte_data):
    """MoE LM through the loop with expert parallelism."""
    cfg = dataclasses.replace(TINY, ffn_type="moe", n_experts=4)
    loop = LoopConfig(
        steps=6,
        batch_size=16,
        log_every=3,
        parallel="dp_ep",
        mesh_axes={"data": 2, "expert": 4},
    )
    summary = train(cfg, HP, loop, byte_data, log_fn=lambda *_: None)
    assert np.isfinite(summary["final_train_loss"])


def test_chunked_loss_step_matches_full(byte_data):
    """A train step with loss_chunk_size set matches the full-logits step."""
    import jax

    from bpe_transformer_tpu.models import init_params
    from bpe_transformer_tpu.optim import adamw_init
    from bpe_transformer_tpu.training.train_step import make_train_step

    cfg_full = TINY
    cfg_chunk = dataclasses.replace(TINY, loss_chunk_size=8)
    params = init_params(jax.random.PRNGKey(0), cfg_full)
    rng = np.random.default_rng(0)
    x = rng.integers(0, cfg_full.vocab_size, size=(8, cfg_full.context_length))
    y = np.roll(x, -1, axis=1)

    p1, s1, m1 = make_train_step(cfg_full, HP)(
        params, adamw_init(params), x, y
    )
    p2, s2, m2 = make_train_step(cfg_chunk, HP)(
        init_params(jax.random.PRNGKey(0), cfg_chunk), None or adamw_init(params), x, y
    )
    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        p1,
        p2,
    )


@pytest.mark.slow
def test_scanned_train_step_matches_sequential():
    """inner_steps>1 (lax.scan over the update) is the SAME math as the
    per-step path: identical params after N updates on identical batches."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from bpe_transformer_tpu.models import TS_TEST_CONFIG, init_params
    from bpe_transformer_tpu.optim import adamw_init
    from bpe_transformer_tpu.training.train_step import (
        TrainHParams,
        make_scanned_train_step,
        make_train_step,
    )

    cfg = dataclasses.replace(TS_TEST_CONFIG, vocab_size=256)
    hp = TrainHParams(warmup_iters=2, cosine_cycle_iters=20)
    rng = np.random.default_rng(0)
    xs = jnp.asarray(rng.integers(0, 256, size=(4, 8, cfg.context_length)))
    ys = jnp.asarray(rng.integers(0, 256, size=(4, 8, cfg.context_length)))

    p1 = init_params(jax.random.PRNGKey(0), cfg)
    s1 = adamw_init(p1)
    step = make_train_step(cfg, hp)
    for i in range(4):
        p1, s1, m1 = step(p1, s1, xs[i], ys[i])

    p2 = init_params(jax.random.PRNGKey(0), cfg)
    s2 = adamw_init(p2)
    scanned = make_scanned_train_step(cfg, hp, 4)
    p2, s2, m2 = scanned(p2, s2, xs, ys)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6
        ),
        p1,
        p2,
    )


def test_loop_inner_steps_trains_and_logs(tmp_path):
    """The loop under inner_steps=4: correct step accounting, loss falls."""
    from bpe_transformer_tpu.models.config import ModelConfig
    from bpe_transformer_tpu.training.loop import LoopConfig, train
    from bpe_transformer_tpu.training.train_step import TrainHParams

    cfg = ModelConfig(vocab_size=128, context_length=16, d_model=32,
                      num_layers=2, num_heads=2, d_ff=64)
    data = np.tile(np.arange(cfg.vocab_size, dtype=np.int32), 100)
    summary = train(
        cfg,
        TrainHParams(warmup_iters=2, cosine_cycle_iters=50),
        LoopConfig(steps=16, batch_size=8, log_every=4, eval_every=1000,
                   checkpoint_every=1000, inner_steps=4),
        train_data=data,
        log_fn=lambda *_: None,
    )
    assert [h["step"] for h in summary["history"]] == [4, 8, 12, 16]
    assert summary["history"][-1]["loss"] < summary["history"][0]["loss"]


@pytest.mark.slow
def test_grad_accum_matches_full_batch_step():
    """accum_steps microbatch gradients averaged in-scan == one step on the
    concatenated batch (the loss is a mean over equal-size microbatches)."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from bpe_transformer_tpu.models import TS_TEST_CONFIG, init_params
    from bpe_transformer_tpu.optim import adamw_init
    from bpe_transformer_tpu.training.train_step import (
        TrainHParams,
        make_grad_accum_train_step,
        make_train_step,
    )

    cfg = dataclasses.replace(TS_TEST_CONFIG, vocab_size=256)
    hp = TrainHParams(warmup_iters=2, cosine_cycle_iters=20)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 256, size=(8, cfg.context_length)))
    y = jnp.asarray(rng.integers(0, 256, size=(8, cfg.context_length)))

    p1 = init_params(jax.random.PRNGKey(0), cfg)
    s1 = adamw_init(p1)
    p1, s1, m1 = make_train_step(cfg, hp)(p1, s1, x, y)

    p2 = init_params(jax.random.PRNGKey(0), cfg)
    s2 = adamw_init(p2)
    step = make_grad_accum_train_step(cfg, hp, 4)
    xs = x.reshape(4, 2, -1)
    ys = y.reshape(4, 2, -1)
    p2, s2, m2 = step(p2, s2, xs, ys)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6
        ),
        p1,
        p2,
    )


def test_loop_grad_accum_trains():
    from bpe_transformer_tpu.models.config import ModelConfig
    from bpe_transformer_tpu.training.loop import LoopConfig, train
    from bpe_transformer_tpu.training.train_step import TrainHParams

    cfg = ModelConfig(vocab_size=128, context_length=16, d_model=32,
                      num_layers=2, num_heads=2, d_ff=64)
    data = np.tile(np.arange(cfg.vocab_size, dtype=np.int32), 100)
    summary = train(
        cfg,
        TrainHParams(warmup_iters=2, cosine_cycle_iters=50),
        LoopConfig(steps=12, batch_size=8, log_every=4, eval_every=1000,
                   checkpoint_every=1000, grad_accum_steps=4),
        train_data=data,
        log_fn=lambda *_: None,
    )
    assert summary["history"][-1]["loss"] < summary["history"][0]["loss"]


@pytest.mark.slow
def test_loop_sp_zigzag_trains_and_evals(tmp_path):
    """parallel='sp' with sp_zigzag=True: the striped schedule trains and
    the dense eval still sees sequences in global order."""
    from bpe_transformer_tpu.models.config import ModelConfig
    from bpe_transformer_tpu.training.loop import LoopConfig, train
    from bpe_transformer_tpu.training.train_step import TrainHParams

    cfg = ModelConfig(vocab_size=128, context_length=32, d_model=32,
                      num_layers=2, num_heads=2, d_ff=64)
    data = np.tile(np.arange(cfg.vocab_size, dtype=np.int32), 100)
    summary = train(
        cfg,
        TrainHParams(warmup_iters=2, cosine_cycle_iters=40),
        LoopConfig(steps=10, batch_size=8, log_every=5, eval_every=10,
                   eval_batches=2, checkpoint_every=1000,
                   parallel="sp", mesh_axes={"data": 2, "seq": 4},
                   sp_zigzag=True),
        train_data=data, val_data=data[:2000],
        log_fn=lambda *_: None,
    )
    assert summary["history"][-1]["loss"] < summary["history"][0]["loss"]
    # Eval ran on globally-ordered data: a near-converged ramp task gives a
    # finite, sane val loss (a permuted eval would blow it up).
    assert np.isfinite(summary["final_val_loss"])


@pytest.mark.slow
def test_loop_sp_grad_accum_trains_and_evals(tmp_path):
    """The training loop drives grad accumulation under the sp (ring
    attention) mesh — the r3 NotImplementedError is gone: microbatch scan
    inside the sharded ring program, eval still on plain batches."""
    from bpe_transformer_tpu.models.config import ModelConfig
    from bpe_transformer_tpu.training.loop import LoopConfig, train
    from bpe_transformer_tpu.training.train_step import TrainHParams

    cfg = ModelConfig(vocab_size=128, context_length=32, d_model=32,
                      num_layers=2, num_heads=2, d_ff=64)
    data = np.tile(np.arange(cfg.vocab_size, dtype=np.int32), 100)
    summary = train(
        cfg,
        TrainHParams(warmup_iters=2, cosine_cycle_iters=40),
        LoopConfig(steps=10, batch_size=8, log_every=5, eval_every=10,
                   eval_batches=2, checkpoint_every=1000,
                   parallel="sp", mesh_axes={"data": 2, "seq": 4},
                   grad_accum_steps=2),  # micro=4 divides data axis (2)
        train_data=data, val_data=data[:2000],
        log_fn=lambda *_: None,
    )
    assert summary["history"][-1]["loss"] < summary["history"][0]["loss"]
    assert np.isfinite(summary["final_val_loss"])


@pytest.mark.slow
def test_loop_sp_inner_steps_with_tail_trains(tmp_path):
    """inner_steps under sp through the loop, with a 1-step TAIL (9 steps,
    stride 4 -> scans of 4+4+1): the tail rebuilds the step via
    build_step(1) and feeds it the unstacked TRAINING layout (zigzag as
    configured) through place_plain, while eval still sees global order."""
    from bpe_transformer_tpu.models.config import ModelConfig
    from bpe_transformer_tpu.training.loop import LoopConfig, train
    from bpe_transformer_tpu.training.train_step import TrainHParams

    cfg = ModelConfig(vocab_size=128, context_length=32, d_model=32,
                      num_layers=2, num_heads=2, d_ff=64)
    data = np.tile(np.arange(cfg.vocab_size, dtype=np.int32), 100)
    summary = train(
        cfg,
        TrainHParams(warmup_iters=2, cosine_cycle_iters=40),
        LoopConfig(steps=9, batch_size=8, log_every=4, eval_every=1000,
                   eval_batches=2, checkpoint_every=1000,
                   parallel="sp", mesh_axes={"data": 2, "seq": 4},
                   sp_zigzag=True, inner_steps=4),
        train_data=data, val_data=data[:2000],
        log_fn=lambda *_: None,
    )
    assert summary["history"][-1]["loss"] < summary["history"][0]["loss"]
    assert np.isfinite(summary["final_val_loss"])


def test_loop_grad_accum_on_mesh_trains(byte_data):
    """The training loop drives grad accumulation under a dp mesh (the
    r2 NotImplementedError is gone): microbatch scan inside the sharded
    step, loss still learns."""
    loop = LoopConfig(
        steps=20,
        batch_size=16,  # micro=8 divides the 8-way data axis
        grad_accum_steps=2,
        parallel="dp",
        mesh_axes={"data": 8},
        log_every=5,
        eval_every=10,  # exercises eval's plain-batch placement under accum
        eval_batches=1,
        checkpoint_every=1000,
    )
    summary = train(TINY, HP, loop, byte_data, byte_data, log_fn=lambda *_: None)
    hist = summary["history"]
    assert hist[-1]["loss"] < hist[0]["loss"]
    assert np.isfinite(summary["final_val_loss"])


def test_loop_inner_steps_on_fsdp_mesh_trains(byte_data):
    """inner_steps under an fsdp mesh, including the short tail (18 steps,
    stride 4 -> tail of 2): the scan compiles inside the GSPMD program."""
    loop = LoopConfig(
        steps=18,
        batch_size=8,
        inner_steps=4,
        parallel="fsdp",
        mesh_axes={"data": 8},
        log_every=4,
        eval_every=1000,
        checkpoint_every=1000,
    )
    summary = train(TINY, HP, loop, byte_data, log_fn=lambda *_: None)
    hist = summary["history"]
    assert hist[-1]["loss"] < hist[0]["loss"]
    assert hist[-1]["step"] == 18


@pytest.mark.slow
def test_loop_pp_grad_accum_trains_and_evals(byte_data, tmp_path):
    """The training loop drives grad accumulation around the pipeline —
    the last pp NotImplementedError is gone: each accumulation slice runs
    the full GPipe schedule, eval still on plain batches via the dense
    forward (VERDICT r4 minor)."""
    loop = LoopConfig(
        steps=8,
        batch_size=16,
        log_every=4,
        eval_every=8,
        eval_batches=2,
        checkpoint_every=1000,
        parallel="pp",
        mesh_axes={"data": 4, "pp": 2},
        pp_microbatches=2,
        grad_accum_steps=2,  # micro=8 divides data axis (4)
    )
    summary = train(
        TINY, HP, loop, byte_data, val_data=byte_data,
        log_fn=lambda *_: None,
    )
    assert summary["history"][-1]["loss"] < summary["history"][0]["loss"]
    assert np.isfinite(summary["final_val_loss"])


@pytest.mark.slow
def test_loop_pp_inner_steps_with_tail_trains(byte_data, tmp_path):
    """inner_steps under pp through the loop, with a 1-step TAIL (9 steps,
    stride 4 -> scans of 4+4+1): the tail rebuilds via build_step(1) and
    feeds the unstacked layout through place_plain."""
    loop = LoopConfig(
        steps=9,
        batch_size=16,
        log_every=4,
        eval_every=1000,
        eval_batches=2,
        checkpoint_every=1000,
        parallel="pp",
        mesh_axes={"data": 4, "pp": 2},
        pp_microbatches=2,
        inner_steps=4,
    )
    summary = train(
        TINY, HP, loop, byte_data, val_data=byte_data,
        log_fn=lambda *_: None,
    )
    assert summary["history"][-1]["loss"] < summary["history"][0]["loss"]
    assert np.isfinite(summary["final_val_loss"])


@pytest.mark.slow
def test_loop_sp_ulysses_trains_and_evals(byte_data, tmp_path):
    """The training loop drives the Ulysses all-to-all schedule (heads
    scattered over the seq axis) end-to-end, eval on the dense forward."""
    loop = LoopConfig(
        steps=8,
        batch_size=16,
        log_every=4,
        eval_every=8,
        eval_batches=2,
        checkpoint_every=1000,
        parallel="sp",
        mesh_axes={"data": 2, "seq": 4},
        sp_ulysses=True,
    )
    summary = train(
        TINY, HP, loop, byte_data, val_data=byte_data,
        log_fn=lambda *_: None,
    )
    assert summary["history"][-1]["loss"] < summary["history"][0]["loss"]
    assert np.isfinite(summary["final_val_loss"])
