"""Pre-tokenization behavior: GPT-2 regex splits, special tokens, chunking."""

from collections import Counter

import pytest

from bpe_transformer_tpu.tokenization import (
    count_pretokens,
    find_chunk_boundaries,
    pretokenize_text,
    split_on_special_tokens,
)
from bpe_transformer_tpu.tokenization.pretokenization import count_pretokens_in_text


def test_gpt2_regex_basic():
    assert pretokenize_text("Hello, how are you?") == [
        b"Hello", b",", b" how", b" are", b" you", b"?",
    ]


def test_gpt2_regex_contractions_numbers_whitespace():
    assert pretokenize_text("I'll pay 100 dollars!!  ") == [
        b"I", b"'ll", b" pay", b" 100", b" dollars", b"!!", b"  ",
    ]


def test_gpt2_regex_unicode():
    assert pretokenize_text("Héllò 🙃") == ["Héllò".encode(), " 🙃".encode()]


def test_split_specials_training_drops_them():
    parts = split_on_special_tokens(
        "a<|endoftext|>b", ["<|endoftext|>"], training=True
    )
    assert parts == ["a", "b"]


def test_split_specials_encoding_keeps_them():
    parts = split_on_special_tokens(
        "a<|endoftext|>b", ["<|endoftext|>"], training=False
    )
    assert parts == ["a", "<|endoftext|>", "b"]


def test_split_overlapping_specials_longest_wins():
    parts = split_on_special_tokens(
        "x<|eot|><|eot|>y",
        ["<|eot|>", "<|eot|><|eot|>"],
        training=False,
    )
    assert parts == ["x", "<|eot|><|eot|>", "y"]


def test_count_pretokens_in_text_drops_specials_when_training():
    counts = count_pretokens_in_text(
        "hi<|endoftext|>hi", ["<|endoftext|>"], training=True
    )
    assert counts == Counter({tuple(b"hi"): 2})


def test_count_pretokens_in_text_keeps_specials_when_encoding():
    counts = count_pretokens_in_text(
        "hi<|endoftext|>hi", ["<|endoftext|>"], training=False
    )
    assert counts[tuple(b"<|endoftext|>")] == 1
    assert counts[tuple(b"hi")] == 2


def test_chunk_boundaries_cover_file_and_land_on_separators(tmp_path):
    path = tmp_path / "data.txt"
    path.write_text("one\ntwo\nthree\nfour\nfive\nsix\nseven\neight\n")
    size = path.stat().st_size
    with open(path, "rb") as f:
        bounds = find_chunk_boundaries(f, 4)
    assert bounds[0] == 0
    assert bounds[-1] == size
    assert bounds == sorted(set(bounds))
    data = path.read_bytes()
    for b in bounds[1:-1]:
        assert data[b : b + 1] == b"\n"


def test_parallel_and_serial_counts_agree(tiny_corpus):
    serial = count_pretokens(tiny_corpus, ["<|endoftext|>"], parallel=False)
    parallel = count_pretokens(
        tiny_corpus, ["<|endoftext|>"], parallel=True, n_workers=2
    )
    assert serial == parallel
    assert sum(serial.values()) > 0
    assert tuple(b"<|endoftext|>") not in serial  # training mode drops specials


@pytest.mark.parametrize("n_chunks", [1, 3, 16])
def test_chunking_never_changes_counts(tiny_corpus, n_chunks):
    from bpe_transformer_tpu.tokenization.pretokenization import (
        count_pretokens_in_chunk,
    )

    with open(tiny_corpus, "rb") as f:
        bounds = find_chunk_boundaries(f, n_chunks, ["<|endoftext|>"])
    total = Counter()
    for start, end in zip(bounds[:-1], bounds[1:]):
        total += count_pretokens_in_chunk(
            tiny_corpus, start, end, True, ["<|endoftext|>"]
        )
    assert total == count_pretokens(tiny_corpus, ["<|endoftext|>"], parallel=False)
