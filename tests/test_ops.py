"""Core-op numerics: reference snapshots where derivable, torch oracles else."""

import numpy as np
import pytest
import torch
import torch.nn.functional as F

import jax.numpy as jnp

from bpe_transformer_tpu.ops import (
    clip_by_global_norm,
    cross_entropy,
    embedding,
    linear,
    rmsnorm,
    rope,
    scaled_dot_product_attention,
    silu,
    softmax,
    swiglu,
)


def _t2n(t):
    return t.detach().cpu().numpy()


# ----------------------------------------------------------- torch oracles


def test_linear_matches_torch():
    rng = np.random.default_rng(0)
    w = rng.standard_normal((128, 64), dtype=np.float32)
    x = rng.standard_normal((4, 12, 64), dtype=np.float32)
    expected = _t2n(torch.from_numpy(x) @ torch.from_numpy(w).T)
    np.testing.assert_allclose(
        np.asarray(linear(jnp.asarray(x), jnp.asarray(w))), expected, atol=1e-5
    )


def test_embedding_matches_torch():
    rng = np.random.default_rng(1)
    table = rng.standard_normal((100, 16), dtype=np.float32)
    ids = rng.integers(0, 100, size=(4, 7))
    expected = _t2n(F.embedding(torch.from_numpy(ids), torch.from_numpy(table)))
    np.testing.assert_allclose(
        np.asarray(embedding(jnp.asarray(table), jnp.asarray(ids))), expected
    )


def test_silu_matches_torch():
    x = np.linspace(-6, 6, 101, dtype=np.float32).reshape(1, -1)
    expected = _t2n(F.silu(torch.from_numpy(x)))
    np.testing.assert_allclose(np.asarray(silu(jnp.asarray(x))), expected, atol=1e-6)


def test_softmax_matches_torch_and_is_overflow_safe():
    rng = np.random.default_rng(2)
    x = rng.standard_normal((3, 5)).astype(np.float32)
    expected = _t2n(F.softmax(torch.from_numpy(x), dim=-1))
    np.testing.assert_allclose(
        np.asarray(softmax(jnp.asarray(x), axis=-1)), expected, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(softmax(jnp.asarray(x) + 100.0, axis=-1)), expected, atol=1e-6
    )
    # other axes too
    expected0 = _t2n(F.softmax(torch.from_numpy(x), dim=0))
    np.testing.assert_allclose(
        np.asarray(softmax(jnp.asarray(x), axis=0)), expected0, atol=1e-6
    )


def test_rmsnorm_matches_torch():
    rng = np.random.default_rng(3)
    x = rng.standard_normal((4, 12, 64)).astype(np.float32)
    w = rng.standard_normal(64).astype(np.float32)
    xt = torch.from_numpy(x)
    expected = _t2n(
        xt * torch.rsqrt(xt.pow(2).mean(-1, keepdim=True) + 1e-5) * torch.from_numpy(w)
    )
    np.testing.assert_allclose(
        np.asarray(rmsnorm(jnp.asarray(x), jnp.asarray(w), eps=1e-5)),
        expected,
        atol=1e-6,
    )


def test_swiglu_matches_torch():
    rng = np.random.default_rng(4)
    d_model, d_ff = 64, 128
    x = rng.standard_normal((4, 12, d_model)).astype(np.float32)
    w1 = rng.standard_normal((d_ff, d_model)).astype(np.float32) * 0.1
    w2 = rng.standard_normal((d_model, d_ff)).astype(np.float32) * 0.1
    w3 = rng.standard_normal((d_ff, d_model)).astype(np.float32) * 0.1
    xt = torch.from_numpy(x)
    expected = _t2n(
        (F.silu(xt @ torch.from_numpy(w1).T) * (xt @ torch.from_numpy(w3).T))
        @ torch.from_numpy(w2).T
    )
    actual = np.asarray(
        swiglu(jnp.asarray(x), jnp.asarray(w1), jnp.asarray(w2), jnp.asarray(w3))
    )
    np.testing.assert_allclose(actual, expected, atol=1e-5)


def test_cross_entropy_matches_torch_and_is_overflow_safe():
    rng = np.random.default_rng(5)
    logits = rng.random((8, 5)).astype(np.float32)
    targets = rng.integers(0, 5, size=8)
    expected = _t2n(
        F.cross_entropy(torch.from_numpy(logits), torch.from_numpy(targets))
    )
    np.testing.assert_allclose(
        np.asarray(cross_entropy(jnp.asarray(logits), jnp.asarray(targets))),
        expected,
        atol=1e-4,
    )
    big = logits * 1000.0
    expected_big = _t2n(
        F.cross_entropy(torch.from_numpy(big), torch.from_numpy(targets))
    )
    np.testing.assert_allclose(
        np.asarray(cross_entropy(jnp.asarray(big), jnp.asarray(targets))),
        expected_big,
        atol=1e-4,
    )


def test_gradient_clipping_matches_torch():
    rng = np.random.default_rng(6)
    grads = {
        "a": rng.standard_normal((5, 5)).astype(np.float32),
        "b": {"c": rng.standard_normal(7).astype(np.float32)},
    }
    max_norm = 1e-2
    params_t = [
        torch.nn.Parameter(torch.zeros(5, 5)),
        torch.nn.Parameter(torch.zeros(7)),
    ]
    params_t[0].grad = torch.from_numpy(grads["a"].copy())
    params_t[1].grad = torch.from_numpy(grads["b"]["c"].copy())
    torch.nn.utils.clip_grad_norm_(params_t, max_norm)

    clipped, norm = clip_by_global_norm(
        {"a": jnp.asarray(grads["a"]), "b": {"c": jnp.asarray(grads["b"]["c"])}},
        max_norm,
    )
    np.testing.assert_allclose(
        np.asarray(clipped["a"]), _t2n(params_t[0].grad), atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(clipped["b"]["c"]), _t2n(params_t[1].grad), atol=1e-6
    )
    assert float(norm) > max_norm  # this fixture definitely clips


def test_gradient_clipping_noop_below_budget():
    g = {"a": jnp.asarray(np.full((2, 2), 1e-4, dtype=np.float32))}
    clipped, _ = clip_by_global_norm(g, max_norm=10.0)
    np.testing.assert_allclose(np.asarray(clipped["a"]), np.asarray(g["a"]))


# --------------------------------------------- reference snapshot parity


def _seeded_qkvm():
    torch.manual_seed(1)
    q = torch.randn(4, 12, 64)
    torch.manual_seed(2)
    k = torch.randn(4, 16, 64)
    torch.manual_seed(3)
    v = torch.randn(4, 16, 64)
    torch.manual_seed(5)
    mask = torch.randn(4, 12, 16) > 0.5
    return q, k, v, mask


def test_sdpa_matches_reference_snapshot(reference_snapshots):
    expected = dict(np.load(reference_snapshots / "test_scaled_dot_product_attention.npz"))[
        "array"
    ]
    q, k, v, mask = _seeded_qkvm()
    actual = scaled_dot_product_attention(
        jnp.asarray(_t2n(q)), jnp.asarray(_t2n(k)), jnp.asarray(_t2n(v)),
        jnp.asarray(_t2n(mask)),
    )
    np.testing.assert_allclose(np.asarray(actual), expected, atol=1e-6, rtol=1e-4)


def test_sdpa_4d_matches_reference_snapshot(reference_snapshots):
    expected = dict(
        np.load(reference_snapshots / "test_4d_scaled_dot_product_attention.npz")
    )["array"]
    q, k, v, mask = _seeded_qkvm()
    reshape = lambda t, s: jnp.asarray(_t2n(t)).reshape(s)
    actual = scaled_dot_product_attention(
        reshape(q, (2, 2, 12, 64)),
        reshape(k, (2, 2, 16, 64)),
        reshape(v, (2, 2, 16, 64)),
        jnp.asarray(_t2n(mask)).reshape(2, 2, 12, 16),
    )
    np.testing.assert_allclose(np.asarray(actual), expected, atol=1e-6, rtol=1e-4)


def test_rope_matches_reference_snapshot(reference_snapshots):
    expected = dict(np.load(reference_snapshots / "test_rope.npz"))["array"]
    torch.manual_seed(4)
    x = torch.randn(4, 12, 64)
    actual = rope(
        jnp.asarray(_t2n(x)), jnp.arange(12), theta=10000.0, max_seq_len=12
    )
    np.testing.assert_allclose(np.asarray(actual), expected, atol=1e-6, rtol=1e-4)


def test_sdpa_fully_masked_rows_are_finite():
    q, k, v, _ = _seeded_qkvm()
    mask = jnp.zeros((4, 12, 16), dtype=bool)  # everything masked
    out = scaled_dot_product_attention(
        jnp.asarray(_t2n(q)), jnp.asarray(_t2n(k)), jnp.asarray(_t2n(v)), mask
    )
    assert np.isfinite(np.asarray(out)).all()


def test_chunked_lm_cross_entropy_matches_full():
    """Chunked loss == full-logits loss, in value AND gradients."""
    import jax

    from bpe_transformer_tpu.ops.losses import chunked_lm_cross_entropy, cross_entropy

    rng = np.random.default_rng(0)
    b, s, d, v = 2, 16, 8, 50
    hidden = jnp.asarray(rng.normal(size=(b, s, d)).astype(np.float32))
    head = jnp.asarray(rng.normal(size=(v, d)).astype(np.float32))
    targets = jnp.asarray(rng.integers(0, v, size=(b, s)))

    full = lambda h, w: cross_entropy(h @ w.T, targets)
    chunked = lambda h, w: chunked_lm_cross_entropy(h, w, targets, chunk_size=4)

    np.testing.assert_allclose(
        float(chunked(hidden, head)), float(full(hidden, head)), rtol=1e-6
    )
    g_full = jax.grad(full, argnums=(0, 1))(hidden, head)
    g_chunk = jax.grad(chunked, argnums=(0, 1))(hidden, head)
    for a, c in zip(g_full, g_chunk):
        np.testing.assert_allclose(np.asarray(c), np.asarray(a), atol=1e-5)

    with pytest.raises(ValueError, match="divisible"):
        chunked_lm_cross_entropy(hidden, head, targets, chunk_size=5)


def test_head_logits_dtype_rule():
    """head_logits: matmul in the hidden's dtype, f32 accumulation/output.

    f32 inputs must be bit-identical to a plain f32 matmul; bf16 inputs
    must produce f32 logits close to the f32 oracle (the head weight is
    read at bf16, so tolerance is bf16-level).
    """
    from bpe_transformer_tpu.ops.core import head_logits

    rng = np.random.default_rng(0)
    hidden32 = jnp.asarray(rng.normal(size=(2, 16, 8)).astype(np.float32))
    head32 = jnp.asarray(rng.normal(size=(50, 8)).astype(np.float32))

    oracle = hidden32 @ head32.T
    exact = head_logits(hidden32, head32)
    assert exact.dtype == jnp.float32
    np.testing.assert_array_equal(np.asarray(exact), np.asarray(oracle))

    mixed = head_logits(hidden32.astype(jnp.bfloat16), head32)
    assert mixed.dtype == jnp.float32  # accumulation/output stay f32
    np.testing.assert_allclose(
        np.asarray(mixed), np.asarray(oracle), rtol=0.05, atol=0.1
    )
