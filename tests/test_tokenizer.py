"""Tokenizer: tiktoken id-level parity, roundtrips, streaming memory bound."""

from __future__ import annotations

import os
import resource
import sys

import pytest

from bpe_transformer_tpu.tokenization import BPETokenizer, train_bpe
from bpe_transformer_tpu.tokenization.gpt2 import load_gpt2_merges, load_gpt2_vocab

try:
    import tiktoken

    HAVE_TIKTOKEN = True
except Exception:  # pragma: no cover
    HAVE_TIKTOKEN = False

requires_tiktoken = pytest.mark.skipif(not HAVE_TIKTOKEN, reason="tiktoken missing")


@pytest.fixture(scope="module")
def tiktoken_gpt2(reference_fixtures):
    """tiktoken's gpt2 encoding, built offline from the fixture artifacts
    (the canonical `get_encoding("gpt2")` downloads them, and this
    environment has no egress)."""
    if not HAVE_TIKTOKEN:
        pytest.skip("tiktoken missing")
    vocab = load_gpt2_vocab(reference_fixtures / "gpt2_vocab.json")
    mergeable = {
        token: idx for idx, token in vocab.items() if token != b"<|endoftext|>"
    }
    return tiktoken.Encoding(
        name="gpt2-offline",
        pat_str=r"""'(?:[sdmt]|ll|ve|re)| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+""",
        mergeable_ranks=mergeable,
        special_tokens={"<|endoftext|>": 50256},
    )


@pytest.fixture(scope="module")
def gpt2_tokenizer(reference_fixtures) -> BPETokenizer:
    vocab = load_gpt2_vocab(reference_fixtures / "gpt2_vocab.json")
    merges = load_gpt2_merges(reference_fixtures / "gpt2_merges.txt")
    return BPETokenizer(vocab, merges, special_tokens=["<|endoftext|>"])


@pytest.fixture(scope="module")
def gpt2_tokenizer_plain(reference_fixtures) -> BPETokenizer:
    vocab = load_gpt2_vocab(reference_fixtures / "gpt2_vocab.json")
    merges = load_gpt2_merges(reference_fixtures / "gpt2_merges.txt")
    return BPETokenizer(vocab, merges)


SIMPLE_STRINGS = [
    "",
    "s",
    "🙃",
    "Hello, how are you?",
    "Héllò hôw are ü? 🙃",
    "   leading spaces and\ttabs\n\nnewlines  ",
    "numbers 12345 and punct!!!",
]


@pytest.mark.parametrize("text", SIMPLE_STRINGS)
def test_roundtrip(gpt2_tokenizer_plain, text):
    assert gpt2_tokenizer_plain.decode(gpt2_tokenizer_plain.encode(text)) == text


@requires_tiktoken
@pytest.mark.parametrize("text", SIMPLE_STRINGS)
def test_matches_tiktoken(gpt2_tokenizer_plain, tiktoken_gpt2, text):
    assert gpt2_tokenizer_plain.encode(text) == tiktoken_gpt2.encode(text)


def test_ascii_tokenization(gpt2_tokenizer):
    ids = gpt2_tokenizer.encode("Hello, how are you?")
    pieces = [gpt2_tokenizer.decode([i]) for i in ids]
    assert pieces == ["Hello", ",", " how", " are", " you", "?"]


def test_special_tokens_preserved(gpt2_tokenizer):
    text = "Héllò hôw <|endoftext|><|endoftext|> are ü? 🙃<|endoftext|>"
    ids = gpt2_tokenizer.encode(text)
    pieces = [gpt2_tokenizer.decode([i]) for i in ids]
    assert pieces.count("<|endoftext|>") == 3
    assert gpt2_tokenizer.decode(ids) == text


@requires_tiktoken
def test_special_tokens_match_tiktoken(gpt2_tokenizer, tiktoken_gpt2):
    text = "Héllò hôw <|endoftext|><|endoftext|> are ü? 🙃<|endoftext|>"
    expected = tiktoken_gpt2.encode(text, allowed_special={"<|endoftext|>"})
    assert gpt2_tokenizer.encode(text) == expected


def test_overlapping_special_tokens(reference_fixtures):
    vocab = load_gpt2_vocab(reference_fixtures / "gpt2_vocab.json")
    merges = load_gpt2_merges(reference_fixtures / "gpt2_merges.txt")
    tok = BPETokenizer(
        vocab, merges, special_tokens=["<|endoftext|>", "<|endoftext|><|endoftext|>"]
    )
    text = "Hello, how <|endoftext|><|endoftext|> are you?<|endoftext|>"
    ids = tok.encode(text)
    pieces = [tok.decode([i]) for i in ids]
    assert pieces.count("<|endoftext|>") == 1
    assert pieces.count("<|endoftext|><|endoftext|>") == 1
    assert tok.decode(ids) == text


@requires_tiktoken
@pytest.mark.parametrize(
    "fixture_name",
    [
        "address.txt",
        "german.txt",
        "tinystories_sample.txt",
        "special_token_trailing_newlines.txt",
        "special_token_double_newlines_non_whitespace.txt",
    ],
)
def test_corpus_matches_tiktoken(gpt2_tokenizer, tiktoken_gpt2, reference_fixtures, fixture_name):
    text = (reference_fixtures / fixture_name).read_text(encoding="utf-8")
    expected = tiktoken_gpt2.encode(text, allowed_special={"<|endoftext|>"})
    ids = gpt2_tokenizer.encode(text)
    assert ids == expected
    assert gpt2_tokenizer.decode(ids) == text


def test_decode_unknown_id_is_replacement(gpt2_tokenizer):
    assert gpt2_tokenizer.decode([10 ** 9]) == "�"


def test_encode_iterable_matches_encode(gpt2_tokenizer, reference_fixtures):
    path = reference_fixtures / "tinystories_sample.txt"
    with open(path, encoding="utf-8") as f:
        streamed = list(gpt2_tokenizer.encode_iterable(f))
    text = path.read_text(encoding="utf-8")
    assert streamed == gpt2_tokenizer.encode(text)


def test_encode_iterable_parallel_matches_serial(gpt2_tokenizer, reference_fixtures):
    path = reference_fixtures / "tinystories_sample.txt"
    with open(path, encoding="utf-8") as f:
        serial = list(gpt2_tokenizer.encode_iterable(f))
    with open(path, encoding="utf-8") as f:
        parallel = list(gpt2_tokenizer.encode_iterable(f, n_workers=2))
    assert serial == parallel


def test_trained_tokenizer_roundtrip(tiny_corpus):
    vocab, merges = train_bpe(
        input_path=tiny_corpus, vocab_size=400, special_tokens=["<|endoftext|>"]
    )
    tok = BPETokenizer(vocab, merges, special_tokens=["<|endoftext|>"])
    text = tiny_corpus.read_text(encoding="utf-8")
    assert tok.decode(tok.encode(text)) == text


@pytest.mark.skipif(
    not sys.platform.startswith("linux"), reason="rlimit support is linux-only"
)
def test_encode_iterable_memory_bounded(gpt2_tokenizer, tmp_path, reference_fixtures):
    """Streaming encode of a ~5 MB corpus must not grow the address space by
    more than 1 MB (reference bound, test_tokenizer.py:416-429)."""
    base = (reference_fixtures / "tinystories_sample.txt").read_text(encoding="utf-8")
    big_path = tmp_path / "big.txt"
    with open(big_path, "w", encoding="utf-8") as f:
        written = 0
        while written < 5_000_000:
            f.write(base)
            written += len(base)

    # Warm the caches/lazy tables outside the limited region.
    gpt2_tokenizer.encode("warmup text so lazy structures exist\n")

    import psutil

    process = psutil.Process(os.getpid())
    prev = resource.getrlimit(resource.RLIMIT_AS)
    resource.setrlimit(
        resource.RLIMIT_AS, (process.memory_info().rss + int(1e6), prev[1])
    )
    try:
        count = 0
        with open(big_path, encoding="utf-8") as f:
            for _ in gpt2_tokenizer.encode_iterable(f):
                count += 1
        assert count > 0
    finally:
        resource.setrlimit(resource.RLIMIT_AS, prev)
