"""Chaos suite: every resilience recovery path exercised end-to-end.

The fault-injection harness (resilience/faults.py) drives real failures at
deterministic points — NaN'd parameters, self-SIGTERM/SIGKILL, corrupted
checkpoint files, failing dataset reads — and these tests assert the
system *recovers*: emergency checkpoints on preemption, checksum-verified
fallback resume past corruption, bounded NaN rollback, and supervised
respawn with crash-loop breaking.

In-process signal/rollback/fallback tests are tier-1; tests that spawn
full CLI child processes (each paying a fresh jax import + compile) are
marked ``slow``.
"""

import dataclasses
import json
import os
import signal
import stat
import subprocess
import sys
import textwrap
import time
from pathlib import Path

import numpy as np
import pytest

from bpe_transformer_tpu.checkpointing import (
    CheckpointCorruptionError,
    load_checkpoint,
    load_checkpoint_with_fallback,
    save_checkpoint,
    save_checkpoint_sharded,
)
from bpe_transformer_tpu.models import ModelConfig
from bpe_transformer_tpu.resilience import (
    EXIT_PREEMPTED,
    FaultInjector,
    FaultPlan,
    GracefulShutdown,
    RollbackBudget,
    RollbackExhausted,
    atomic_write_json,
    corrupt_file,
    gc_checkpoints,
    latest_valid_checkpoint,
    quarantine,
    supervise,
    verify_checkpoint,
)
from bpe_transformer_tpu.telemetry.watchdog import NonFiniteError
from bpe_transformer_tpu.training import LoopConfig, TrainHParams, train
from bpe_transformer_tpu.training.cli import main as cli_main

REPO = Path(__file__).resolve().parent.parent

TINY = ModelConfig(
    vocab_size=128, context_length=16, d_model=32,
    num_layers=2, num_heads=2, d_ff=64,
)
HP = TrainHParams(warmup_iters=2, cosine_cycle_iters=50)


@pytest.fixture(scope="module")
def ramp_data():
    return np.tile(np.arange(TINY.vocab_size, dtype=np.uint16), 200)


def _read_jsonl(path):
    return [json.loads(line) for line in Path(path).read_text().splitlines()]


def _quiet(*_a, **_k):
    pass


# ---------------------------------------------------------------- integrity


def test_dense_checkpoint_sidecar_and_verify(tmp_path):
    """Dense saves stamp a CRC32 sidecar; verify passes clean files, fails
    a bit flip (size unchanged — only a checksum catches it) and a
    truncation."""
    path = tmp_path / "step_00000002.ckpt"
    save_checkpoint(path, params={"w": np.arange(8.0)}, iteration=2)
    assert (tmp_path / "step_00000002.ckpt.crc32.json").exists()
    assert verify_checkpoint(path).ok

    corrupt_file(path, mode="flip")
    result = verify_checkpoint(path)
    assert not result.ok
    assert any("crc32 mismatch" in p for p in result.problems)

    save_checkpoint(path, params={"w": np.arange(8.0)}, iteration=2)
    corrupt_file(path, mode="truncate", nbytes=16)
    result = verify_checkpoint(path)
    assert not result.ok
    assert any("truncated" in p for p in result.problems)


def test_dense_checkpoint_without_sidecar_passes_with_warning(tmp_path):
    """A pre-integrity checkpoint (no sidecar) is NOT treated as corrupt —
    absence of evidence only warns."""
    path = tmp_path / "old.ckpt"
    save_checkpoint(path, params={"w": np.ones(3)}, iteration=1)
    (tmp_path / "old.ckpt.crc32.json").unlink()
    result = verify_checkpoint(path)
    assert result.ok
    assert result.warnings


def test_sharded_manifest_checksums_and_verify(tmp_path):
    """Sharded saves stamp per-file CRC32s into the manifest; a truncated
    shard is detected BY NAME, and a mangled manifest fails outright."""
    path = tmp_path / "sh.ckpt"
    save_checkpoint_sharded(
        path, params={"w": np.arange(12.0).reshape(3, 4), "b": np.ones(3)},
        iteration=7,
    )
    manifest = json.loads((path / "manifest.json").read_text())
    assert "treedef.pkl" in manifest["checksums"]
    assert sum(1 for f in manifest["checksums"] if f.endswith(".npy")) == 2
    assert verify_checkpoint(path).ok

    corrupt_file(path / "leaf_00001.npy", mode="truncate", nbytes=8)
    result = verify_checkpoint(path)
    assert not result.ok
    assert any("leaf_00001.npy" in p for p in result.problems)

    (path / "manifest.json").write_text("{not json")
    assert not verify_checkpoint(path).ok


def test_verify_missing_checkpoint(tmp_path):
    result = verify_checkpoint(tmp_path / "nope.ckpt")
    assert not result.ok and result.format == "missing"


def test_quarantine_moves_snapshot_and_sidecar(tmp_path):
    path = tmp_path / "step_00000004.ckpt"
    save_checkpoint(path, params={"w": np.ones(2)}, iteration=4)
    moved = quarantine(path)
    assert moved.name == "step_00000004.ckpt.corrupt"
    assert not path.exists()
    assert moved.exists()
    assert moved.with_name(moved.name + ".crc32.json").exists()
    # Quarantined snapshots are invisible to discovery.
    assert latest_valid_checkpoint(tmp_path) is None


def test_load_fallback_quarantines_and_uses_prior_snapshot(tmp_path):
    """A corrupt newest snapshot falls back to the newest PRIOR valid one;
    the corrupt copy is quarantined (never deleted)."""
    for step in (2, 4):
        save_checkpoint(
            tmp_path / f"step_{step:08d}.ckpt",
            params={"w": np.full(4, float(step))},
            iteration=step,
        )
    corrupt_file(tmp_path / "step_00000004.ckpt", mode="flip")
    payload, used = load_checkpoint_with_fallback(
        tmp_path / "step_00000004.ckpt"
    )
    assert used.name == "step_00000002.ckpt"
    assert payload["iteration"] == 2
    assert (tmp_path / "step_00000004.ckpt.corrupt").exists()

    # Everything corrupt -> a structured error, with the bad snapshots
    # quarantined along the way.
    corrupt_file(tmp_path / "step_00000002.ckpt", mode="truncate")
    with pytest.raises(CheckpointCorruptionError):
        load_checkpoint_with_fallback(tmp_path / "step_00000002.ckpt")


def test_fallback_never_fast_forwards_past_requested_snapshot(tmp_path):
    """An explicitly requested OLD snapshot that fails must not silently
    resume from a NEWER sibling (re-branching before a divergence is a
    deliberate act); only strictly-prior snapshots are candidates."""
    for step in (2, 4, 9):
        save_checkpoint(
            tmp_path / f"step_{step:08d}.ckpt",
            params={"w": np.full(2, float(step))}, iteration=step,
        )
    corrupt_file(tmp_path / "step_00000004.ckpt", mode="flip")
    payload, used = load_checkpoint_with_fallback(
        tmp_path / "step_00000004.ckpt"
    )
    assert used.name == "step_00000002.ckpt"  # prior, never step_9
    assert payload["iteration"] == 2


def test_load_failure_of_verified_snapshot_reraises_without_quarantine(
    tmp_path,
):
    """Intact bytes that fail to LOAD are a caller/config/environment
    error, not corruption: the error surfaces and nothing is renamed —
    a one-flag typo must not serially quarantine valid snapshots."""
    for step in (2, 4):
        save_checkpoint(
            tmp_path / f"step_{step:08d}.ckpt",
            params={"w": np.ones(2)}, iteration=step,
        )

    def exploding_loader(path):
        raise RuntimeError("mesh mismatch: pp axis is 2, checkpoint has 4")

    with pytest.raises(RuntimeError, match="mesh mismatch"):
        load_checkpoint_with_fallback(
            tmp_path / "step_00000004.ckpt", loader=exploding_loader
        )
    names = sorted(p.name for p in tmp_path.iterdir())
    assert not any(".corrupt" in n for n in names)


def test_verify_fast_mode_skips_crc_but_catches_truncation(tmp_path):
    path = tmp_path / "step_00000002.ckpt"
    save_checkpoint(path, params={"w": np.arange(64.0)}, iteration=2)
    assert verify_checkpoint(path, deep=False).ok
    corrupt_file(path, mode="flip")
    # Fast mode trades bit-rot detection for O(stat) cost...
    assert verify_checkpoint(path, deep=False).ok
    assert not verify_checkpoint(path).ok
    # ...but still catches truncation via the size record.
    corrupt_file(path, mode="truncate", nbytes=8)
    assert not verify_checkpoint(path, deep=False).ok


def test_resume_falls_back_past_corrupt_snapshot(ramp_data, tmp_path):
    """ACCEPTANCE (b): train -> corrupt the newest snapshot AND the latest
    copy -> resume detects it by checksum, falls back to the prior
    snapshot, and the resumed run completes."""
    ckpt = tmp_path / "ckpt"
    loop = LoopConfig(
        steps=10, batch_size=4, log_every=5, eval_every=1000,
        checkpoint_every=5, checkpoint_dir=str(ckpt),
    )
    train(TINY, HP, loop, ramp_data, log_fn=_quiet)
    assert (ckpt / "step_00000005.ckpt").exists()
    corrupt_file(ckpt / "step_00000010.ckpt", mode="flip")
    corrupt_file(ckpt / "latest.ckpt", mode="truncate")

    summary = train(
        TINY, HP, dataclasses.replace(loop, steps=15), ramp_data,
        resume_from=ckpt, log_fn=_quiet,
    )
    assert summary["history"][-1]["step"] == 15
    # Restart point was the fallback snapshot: steps 6-10 were retrained.
    assert summary["history"][0]["step"] == 10
    corrupted = {p.name for p in ckpt.iterdir() if ".corrupt" in p.name}
    assert any("latest.ckpt.corrupt" in n for n in corrupted)
    assert any("step_00000010.ckpt.corrupt" in n for n in corrupted)


# ---------------------------------------------------------- verify-ckpt CLI


def test_verify_checkpoint_cli_smoke(tmp_path, capsys):
    path = tmp_path / "m.ckpt"
    save_checkpoint(path, params={"w": np.ones(4)}, iteration=3)
    assert cli_main(["verify-checkpoint", str(path)]) == 0
    assert "OK" in capsys.readouterr().out

    corrupt_file(path, mode="flip")
    assert cli_main(["verify-checkpoint", str(path), "--json"]) == 1
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["ok"] is False
    assert verdict["format"] == "dense"


def test_verify_checkpoint_cli_is_jax_free(tmp_path):
    """The fast path never imports jax — safe on a login host while the
    pod trains (and fast: no backend init)."""
    path = tmp_path / "m.ckpt"
    save_checkpoint(path, params={"w": np.ones(4)}, iteration=3)
    code = textwrap.dedent(
        f"""
        import sys
        from bpe_transformer_tpu.training.cli import main
        rc = main(["verify-checkpoint", {str(path)!r}])
        assert rc == 0, rc
        assert "jax" not in sys.modules, "verify-checkpoint imported jax"
        """
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=REPO, capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": ""},
    )
    assert proc.returncode == 0, proc.stderr


# --------------------------------------------------------------- preemption


def test_graceful_shutdown_flag_and_double_signal():
    stop = GracefulShutdown()
    assert stop.install()
    try:
        assert not stop.triggered
        os.kill(os.getpid(), signal.SIGTERM)
        assert stop.triggered
        assert stop.signame == "SIGTERM"
        # The second signal escalates: cooperative window is over.
        with pytest.raises(KeyboardInterrupt):
            os.kill(os.getpid(), signal.SIGTERM)
    finally:
        stop.uninstall()


def test_preemption_writes_emergency_checkpoint_and_resumes(
    ramp_data, tmp_path
):
    """ACCEPTANCE (a), in-process: SIGTERM mid-run -> stop at the next step
    boundary, emergency checkpoint, kind="preemption" record, footered
    stream — and --resume continues from the exact stop step."""
    ckpt = tmp_path / "ckpt"
    jsonl = tmp_path / "m.jsonl"
    loop = LoopConfig(
        steps=20, batch_size=4, log_every=2, eval_every=1000,
        checkpoint_every=20, checkpoint_dir=str(ckpt),
        metrics_jsonl=str(jsonl),
    )
    injector = FaultInjector(FaultPlan(preempt_at_step=6))
    summary = train(
        TINY, HP, loop, ramp_data, log_fn=_quiet, fault_injector=injector
    )
    assert summary["preempted"] == "SIGTERM"
    stop_step = summary["stopped_at_step"]
    # Stopped within one log window of the signal, never before it.
    assert 6 <= stop_step <= 6 + loop.log_every

    records = _read_jsonl(jsonl)
    pre = [r for r in records if r.get("kind") == "preemption"]
    assert len(pre) == 1
    assert pre[0]["signal"] == "SIGTERM"
    assert pre[0]["step"] == stop_step
    emergency = Path(pre[0]["checkpoint"])
    assert emergency.exists()
    assert verify_checkpoint(emergency).ok
    footer = records[-1]
    assert footer["kind"] == "footer"
    assert footer["clean"] is True and footer["preempted"] == "SIGTERM"

    resumed = train(
        TINY, HP, loop, ramp_data, resume_from=ckpt, log_fn=_quiet
    )
    assert "preempted" not in resumed
    assert resumed["history"][-1]["step"] == 20
    # Zero completed steps lost: the resume started at the stop step.
    assert load_checkpoint(ckpt / "latest.ckpt")["iteration"] == 20


def test_preemption_skips_emergency_save_of_poisoned_state(
    ramp_data, tmp_path
):
    """A SIGTERM landing between a NaN-producing step and the detection
    boundary must NOT snapshot the poisoned state — the prior clean
    snapshot stays the newest resume target (else rollback-on-resume would
    restore the NaN over and over until its budget died)."""
    ckpt = tmp_path / "ckpt"
    jsonl = tmp_path / "m.jsonl"
    loop = LoopConfig(
        steps=40, batch_size=4, log_every=1000, eval_every=1000,
        checkpoint_every=4, checkpoint_dir=str(ckpt),
        metrics_jsonl=str(jsonl),
    )
    # NaN fires after step 5; SIGTERM at the step-6 boundary — before any
    # log boundary could detect the poison.
    injector = FaultInjector(FaultPlan(nan_at_step=5, preempt_at_step=6))
    summary = train(
        TINY, HP, loop, ramp_data, log_fn=_quiet, fault_injector=injector
    )
    assert summary["preempted"] == "SIGTERM"
    pre = [r for r in _read_jsonl(jsonl) if r.get("kind") == "preemption"][0]
    assert pre["checkpoint"] is None
    assert pre["skipped_nonfinite_state"] is True
    # The clean step-4 snapshot is still the newest resume target.
    assert load_checkpoint(ckpt / "latest.ckpt")["iteration"] == 4
    assert latest_valid_checkpoint(ckpt) is not None


def test_preemption_without_checkpoint_dir_still_records(ramp_data, tmp_path):
    jsonl = tmp_path / "m.jsonl"
    loop = LoopConfig(
        steps=20, batch_size=4, log_every=2, eval_every=1000,
        checkpoint_every=1000, metrics_jsonl=str(jsonl),
    )
    injector = FaultInjector(FaultPlan(preempt_at_step=4))
    summary = train(
        TINY, HP, loop, ramp_data, log_fn=_quiet, fault_injector=injector
    )
    assert summary["preempted"] == "SIGTERM"
    pre = [r for r in _read_jsonl(jsonl) if r.get("kind") == "preemption"]
    assert pre and pre[0]["checkpoint"] is None


# ----------------------------------------------------------- NaN rollback


def test_nan_rollback_recovers_and_localizes(ramp_data, tmp_path):
    """ACCEPTANCE (c): an injected NaN under on_nonfinite="rollback"
    reloads the last checkpoint, advances the data window, and the run
    reaches its final step — with kind="recovery" records naming the
    tensor path (PR-4 dynamics localization)."""
    ckpt = tmp_path / "ckpt"
    jsonl = tmp_path / "m.jsonl"
    loop = LoopConfig(
        steps=24, batch_size=4, log_every=4, eval_every=1000,
        checkpoint_every=8, checkpoint_dir=str(ckpt),
        metrics_jsonl=str(jsonl), dynamics_every=4,
        watchdog=True, watchdog_policy="rollback", max_rollbacks=3,
    )
    injector = FaultInjector(FaultPlan(nan_at_step=10))
    summary = train(
        TINY, HP, loop, ramp_data, log_fn=_quiet, fault_injector=injector
    )
    assert summary["history"][-1]["step"] == 24
    assert np.isfinite(summary["final_train_loss"])
    assert summary["rollbacks"] == 1

    records = _read_jsonl(jsonl)
    rec = [r for r in records if r.get("kind") == "recovery"]
    assert len(rec) == 1
    assert rec[0]["restored_step"] == 8
    assert rec[0]["step"] == 12
    assert rec[0]["lost_steps"] == 4
    assert rec[0]["nonfinite_path"].startswith("params/")
    # The dump-then-act contract: the nonfinite event landed too.
    assert any(
        r.get("kind") == "event" and r.get("name") == "nonfinite"
        for r in records
    )
    footer = records[-1]
    assert footer["clean"] is True


def test_rollback_budget_breaker():
    budget = RollbackBudget(max_rollbacks=2, min_progress_steps=5)
    assert budget.note(10) == 1          # first: always allowed
    assert budget.note(12) == 2          # only 2 steps of progress
    with pytest.raises(RollbackExhausted):
        budget.note(13)                  # third without progress: trip
    # Progress resets the consecutive counter.
    budget = RollbackBudget(max_rollbacks=2, min_progress_steps=5)
    budget.note(10)
    budget.note(12)
    assert budget.note(40) == 3          # 28 steps of progress: forgiven
    # max_rollbacks=0 means the first detection aborts.
    with pytest.raises(RollbackExhausted):
        RollbackBudget(max_rollbacks=0).note(1)


def test_rollback_exhaustion_aborts_loop(ramp_data, tmp_path):
    """The loop-level breaker: with max_rollbacks=0 the first non-finite
    detection escalates to NonFiniteError (after dumping evidence)."""
    loop = LoopConfig(
        steps=24, batch_size=4, log_every=4, eval_every=1000,
        checkpoint_every=8, checkpoint_dir=str(tmp_path / "ckpt"),
        metrics_jsonl=str(tmp_path / "m.jsonl"),
        watchdog=True, watchdog_policy="rollback", max_rollbacks=0,
    )
    injector = FaultInjector(FaultPlan(nan_at_step=10))
    with pytest.raises(NonFiniteError, match="rollback budget exhausted"):
        train(
            TINY, HP, loop, ramp_data, log_fn=_quiet,
            fault_injector=injector,
        )
    events = [
        r for r in _read_jsonl(tmp_path / "m.jsonl")
        if r.get("kind") == "event" and r.get("name") == "recovery_abort"
    ]
    assert events


def test_rollback_without_any_checkpoint_aborts(ramp_data, tmp_path):
    """NaN before the first checkpoint: nothing to restore -> escalate
    rather than loop."""
    loop = LoopConfig(
        steps=24, batch_size=4, log_every=4, eval_every=1000,
        checkpoint_every=1000, checkpoint_dir=str(tmp_path / "ckpt"),
        watchdog=True, watchdog_policy="rollback",
    )
    injector = FaultInjector(FaultPlan(nan_at_step=2))
    with pytest.raises(NonFiniteError, match="no valid checkpoint"):
        train(
            TINY, HP, loop, ramp_data, log_fn=_quiet,
            fault_injector=injector,
        )


def test_rollback_config_validation(ramp_data, tmp_path):
    base = dict(steps=8, batch_size=4, watchdog=True,
                watchdog_policy="rollback")
    with pytest.raises(ValueError, match="needs checkpoint_dir"):
        train(TINY, HP, LoopConfig(**base), ramp_data, log_fn=_quiet)
    with pytest.raises(ValueError, match="multiple of log_every"):
        train(
            TINY, HP,
            LoopConfig(**base, checkpoint_dir=str(tmp_path), log_every=4,
                       checkpoint_every=6),
            ramp_data, log_fn=_quiet,
        )
    with pytest.raises(ValueError, match='parallel="pp"'):
        train(
            TINY, HP,
            LoopConfig(**base, checkpoint_dir=str(tmp_path), parallel="pp",
                       mesh_axes={"pp": 2}),
            ramp_data, log_fn=_quiet,
        )


# ---------------------------------------------------------------- retention


def test_gc_keeps_newest_protects_latest_and_corrupt(tmp_path):
    for step in (2, 4, 6, 8):
        save_checkpoint(
            tmp_path / f"step_{step:08d}.ckpt",
            params={"w": np.ones(2)}, iteration=step,
        )
    # latest points (symlink) at an OLD snapshot — must survive GC anyway.
    (tmp_path / "latest.ckpt").symlink_to("step_00000004.ckpt")
    quarantine(tmp_path / "step_00000002.ckpt")
    # Stranded crash debris, older than every snapshot.
    debris = tmp_path / "step_00000004.ckpt.tmpXYZ"
    debris.write_bytes(b"partial")
    old = time.time() - 3600
    os.utime(debris, (old, old))

    removed = gc_checkpoints(tmp_path, keep=1)
    names = {p.name for p in tmp_path.iterdir()}
    assert "step_00000008.ckpt" in names          # newest kept
    assert "step_00000004.ckpt" in names          # latest's target kept
    assert "step_00000006.ckpt" not in names      # rotated out
    assert "step_00000002.ckpt.corrupt" in names  # evidence kept
    assert "step_00000004.ckpt.tmpXYZ" not in names  # debris reclaimed
    assert {p.name for p in removed} >= {
        "step_00000006.ckpt", "step_00000004.ckpt.tmpXYZ",
    }


def test_loop_retention_gc(ramp_data, tmp_path):
    ckpt = tmp_path / "ckpt"
    loop = LoopConfig(
        steps=15, batch_size=4, log_every=5, eval_every=1000,
        checkpoint_every=5, checkpoint_dir=str(ckpt), keep_checkpoints=2,
    )
    train(TINY, HP, loop, ramp_data, log_fn=_quiet)
    snapshots = sorted(
        p.name for p in ckpt.iterdir()
        if p.name.startswith("step_") and p.name.endswith(".ckpt")
    )
    assert snapshots == ["step_00000010.ckpt", "step_00000015.ckpt"]
    assert (ckpt / "latest.ckpt").exists()
    assert load_checkpoint(ckpt / "latest.ckpt")["iteration"] == 15


# ------------------------------------------------------- atomic JSON writes


def test_atomic_write_json_replaces_and_survives_failure(tmp_path):
    target = tmp_path / "summary.json"
    atomic_write_json(target, {"ok": 1})
    assert json.loads(target.read_text()) == {"ok": 1}

    class Boom:
        """json.dump raises mid-serialization."""

        def __iter__(self):
            raise RuntimeError("boom")

    with pytest.raises(TypeError):
        atomic_write_json(target, {"bad": Boom()})
    # Original intact, no tmp litter.
    assert json.loads(target.read_text()) == {"ok": 1}
    assert [p.name for p in tmp_path.iterdir()] == ["summary.json"]


# ------------------------------------------------------- dataset validation


def test_token_file_geometry_validation(tmp_path):
    from bpe_transformer_tpu.data import check_dataset_geometry, load_token_file

    empty = tmp_path / "empty.bin"
    empty.write_bytes(b"")
    with pytest.raises(ValueError, match="empty"):
        load_token_file(empty)

    odd = tmp_path / "odd.bin"
    odd.write_bytes(b"\x00" * 7)
    with pytest.raises(ValueError, match="not a multiple"):
        load_token_file(odd, "uint16")

    with pytest.raises(FileNotFoundError):
        load_token_file(tmp_path / "missing.bin")

    with pytest.raises(ValueError, match="context_length \\+ 1"):
        check_dataset_geometry(np.zeros(10, np.uint16), 16, 4)


def test_train_rejects_undersized_dataset_up_front(tmp_path):
    tiny = np.zeros(TINY.context_length, dtype=np.uint16)  # one short
    with pytest.raises(ValueError, match="too short"):
        train(
            TINY, HP, LoopConfig(steps=4, batch_size=4), tiny,
            log_fn=_quiet,
        )


def test_injected_dataset_read_failure_crashes_cleanly(ramp_data, tmp_path):
    """The fail-read fault surfaces as the injected OSError (supervisor
    respawn territory) and the telemetry stream still gets its footer."""
    jsonl = tmp_path / "m.jsonl"
    injector = FaultInjector(FaultPlan(fail_read_at_step=3))
    with pytest.raises(OSError, match="injected dataset read failure"):
        train(
            TINY, HP,
            LoopConfig(steps=8, batch_size=4, log_every=2,
                       metrics_jsonl=str(jsonl)),
            ramp_data, log_fn=_quiet, fault_injector=injector,
        )
    footer = _read_jsonl(jsonl)[-1]
    assert footer["kind"] == "footer" and footer["clean"] is False


# --------------------------------------------------------------- supervisor


def _stub_child(tmp_path, script_body: str) -> list[str]:
    """A jax-free stand-in for the training child: the supervisor only
    sees argv + exit codes, so the protocol is testable in milliseconds."""
    script = tmp_path / "child.py"
    script.write_text(textwrap.dedent(script_body))
    script.chmod(script.stat().st_mode | stat.S_IXUSR)
    return [sys.executable, str(script)]


def test_supervisor_respawns_until_success_with_auto_resume(tmp_path):
    """ACCEPTANCE (d), protocol level: crash -> preemption -> success, each
    respawn auto-resuming from the newest VALID snapshot (the corrupt
    newer one is skipped)."""
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    for step in (2, 4):
        save_checkpoint(
            ckpt / f"step_{step:08d}.ckpt",
            params={"w": np.ones(2)}, iteration=step,
        )
    # Truncation, not a bit flip: the supervisor scans in FAST mode
    # (sizes only — a deep CRC sweep per respawn would triple the restart
    # I/O on multi-GB snapshots); bit rot is the child's deep re-verify's
    # job at load time.
    corrupt_file(ckpt / "step_00000004.ckpt", mode="truncate")

    state = tmp_path / "runs"
    child = _stub_child(
        tmp_path,
        f"""
        import json, sys
        from pathlib import Path
        state = Path({str(state)!r})
        state.mkdir(exist_ok=True)
        n = len(list(state.glob("run_*")))
        (state / f"run_{{n}}.json").write_text(json.dumps(sys.argv[1:]))
        sys.exit([82, {EXIT_PREEMPTED}, 0][n])
        """,
    )
    rc = supervise(
        ["train", "--steps", "9", "--checkpoint-dir", str(ckpt)],
        ckpt,
        max_restarts=3,
        backoff_s=0.01,
        child_cmd=child,
        log=_quiet,
        sleep=lambda _s: None,
    )
    assert rc == 0
    runs = sorted(state.glob("run_*.json"))
    assert len(runs) == 3
    for run in runs:
        argv = json.loads(run.read_text())
        # Auto-resume targets the newest snapshot that VERIFIES — the
        # corrupt step_4 is skipped in favor of step_2.
        assert argv[argv.index("--resume") + 1].endswith("step_00000002.ckpt")
        assert "--supervise" not in argv


def test_supervisor_preserves_user_warm_start_resume(tmp_path):
    """With no supervisor snapshot yet, a user-supplied --resume (a
    warm-start from elsewhere) must reach the first child unchanged — and
    be replaced only once the supervisor has its own newer snapshot."""
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    warm = tmp_path / "pretrained.ckpt"
    state = tmp_path / "runs"
    child = _stub_child(
        tmp_path,
        f"""
        import json, sys
        from pathlib import Path
        state = Path({str(state)!r}); state.mkdir(exist_ok=True)
        n = len(list(state.glob("run_*")))
        (state / f"run_{{n}}.json").write_text(json.dumps(sys.argv[1:]))
        if n == 0:
            # First run "trains a bit": leave a valid snapshot behind.
            sys.path.insert(0, {str(REPO)!r})
            import numpy as np
            from bpe_transformer_tpu.checkpointing import save_checkpoint
            save_checkpoint(
                Path({str(ckpt)!r}) / "step_00000006.ckpt",
                params={{"w": np.ones(2)}}, iteration=6,
            )
            sys.exit(1)
        sys.exit(0)
        """,
    )
    rc = supervise(
        ["train", "--resume", str(warm)], ckpt,
        max_restarts=2, backoff_s=0.01,
        child_cmd=child, log=_quiet, sleep=lambda _s: None,
    )
    assert rc == 0
    first = json.loads((state / "run_0.json").read_text())
    second = json.loads((state / "run_1.json").read_text())
    assert first[first.index("--resume") + 1] == str(warm)
    assert second[second.index("--resume") + 1].endswith(
        "step_00000006.ckpt"
    )


def test_supervisor_crash_loop_breaker(tmp_path):
    """A child that always crashes without checkpoint progress exhausts
    max_restarts and the supervisor propagates its exit code."""
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    state = tmp_path / "runs"
    child = _stub_child(
        tmp_path,
        f"""
        import sys
        from pathlib import Path
        state = Path({str(state)!r}); state.mkdir(exist_ok=True)
        (state / f"run_{{len(list(state.glob('run_*')))}}").touch()
        sys.exit(7)
        """,
    )
    rc = supervise(
        ["train"], ckpt, max_restarts=2, backoff_s=0.01,
        child_cmd=child, log=_quiet, sleep=lambda _s: None,
    )
    assert rc == 7
    assert len(list(state.glob("run_*"))) == 3  # initial + 2 restarts


def test_supervisor_forwards_stop_signal_and_does_not_respawn(tmp_path):
    """Under docker/k8s the preemption SIGTERM lands on the supervisor
    (often PID 1): it must forward the signal to the child (whose graceful
    path runs) and then STOP — a signalled supervisor is being told to
    exit, not to restart."""
    import threading

    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    state = tmp_path / "runs"
    child = _stub_child(
        tmp_path,
        f"""
        import signal, sys, time
        from pathlib import Path
        state = Path({str(state)!r}); state.mkdir(exist_ok=True)
        (state / f"run_{{len(list(state.glob('run_*')))}}").touch()
        signal.signal(signal.SIGTERM, lambda *a: sys.exit({EXIT_PREEMPTED}))
        time.sleep(60)
        sys.exit(0)
        """,
    )
    threading.Timer(
        1.5, lambda: os.kill(os.getpid(), signal.SIGTERM)
    ).start()
    rc = supervise(
        ["train"], ckpt, max_restarts=3, backoff_s=0.01,
        child_cmd=child, log=_quiet,
    )
    assert rc == EXIT_PREEMPTED
    assert len(list(state.glob("run_*"))) == 1  # no respawn after the stop


def test_supervisor_flag_stripping():
    from bpe_transformer_tpu.resilience.supervisor import (
        strip_supervisor_flags,
    )

    argv = [
        "train", "--supervise", "--max-restarts", "4",
        "--restart-backoff=0.5", "--steps", "10",
    ]
    assert strip_supervisor_flags(argv) == ["train", "--steps", "10"]


# ---------------------------------------------------- report/monitor surface


def test_report_recovery_section_pinned_by_fixture():
    from bpe_transformer_tpu.telemetry.report import (
        load_records,
        render_report,
        summarize,
    )

    records = load_records(REPO / "tests" / "fixtures" / "recovery_tiny.jsonl")
    s = summarize(records)
    rc = s["recovery"]
    assert rc["rollbacks"] == 1
    assert rc["lost_steps_total"] == 4
    assert rc["nonfinite_paths"] == ["params/layers.0.attn.k_proj"]
    assert rc["preemptions"][0]["signal"] == "SIGTERM"
    text = render_report(records)
    assert "== recovery ==" in text
    assert "rollback #1: step 12 -> restored 8" in text
    assert "preemption at step 18 (SIGTERM" in text
    assert any("preempted at step 18" in a for a in s["anomalies"])


def test_monitor_folds_recovery_and_preemption():
    from bpe_transformer_tpu.telemetry.monitor import fold_records, render_frame
    from bpe_transformer_tpu.telemetry.report import load_records

    records = load_records(REPO / "tests" / "fixtures" / "recovery_tiny.jsonl")
    state = fold_records(records)
    assert state["rollbacks"] == 1
    assert state["preempted"] == "SIGTERM"
    frame = render_frame(state, "fixture")
    assert "rollbacks 1" in frame
    assert "[preempted SIGTERM]" in frame


def test_new_record_kinds_registered():
    from bpe_transformer_tpu.telemetry.schema import (
        RECORD_SCHEMAS,
        validate_record,
    )

    assert "preemption" in RECORD_SCHEMAS
    assert "recovery" in RECORD_SCHEMAS
    assert validate_record(
        {"kind": "recovery", "t": 1.0, "step": 8, "restored_step": 4,
         "rollbacks": 1}
    ) == []
    assert validate_record({"kind": "preemption", "t": 1.0, "step": 8}) != []


# ------------------------------------------------- process-level chaos (slow)


def _spawn_cli_train(ckpt_dir, jsonl, data_path, steps, extra=()):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    return subprocess.Popen(
        [
            sys.executable, "-m", "bpe_transformer_tpu.training.cli",
            "train",
            "--data", str(data_path),
            "--model-config", str(Path(ckpt_dir).parent / "model.json"),
            "--steps", str(steps),
            "--batch-size", "4",
            "--log-every", "2",
            "--eval-every", "1000",
            "--checkpoint-every", "50",
            "--checkpoint-dir", str(ckpt_dir),
            "--metrics-jsonl", str(jsonl),
            "--warmup", "2",
            *extra,
        ],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )


@pytest.fixture()
def cli_workspace(tmp_path, ramp_data):
    (tmp_path / "tokens.bin").write_bytes(ramp_data.tobytes())
    TINY.to_json(tmp_path / "model.json")
    return tmp_path


@pytest.mark.slow
def test_cli_sigterm_exit_code_and_resume(cli_workspace):
    """ACCEPTANCE (a), process level: SIGTERM a real CLI run mid-training
    -> EXIT_PREEMPTED + emergency checkpoint; a resume run completes with
    exit 0."""
    ckpt = cli_workspace / "ckpt"
    jsonl = cli_workspace / "m.jsonl"
    proc = _spawn_cli_train(ckpt, jsonl, cli_workspace / "tokens.bin", 4000)
    try:
        deadline = time.time() + 240
        while time.time() < deadline and not jsonl.exists():
            time.sleep(0.2)
        # Let a couple of log windows land so the kill is mid-run.
        while time.time() < deadline:
            if jsonl.exists() and len(jsonl.read_text().splitlines()) >= 4:
                break
            time.sleep(0.2)
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == EXIT_PREEMPTED, out
    pre = [r for r in _read_jsonl(jsonl) if r.get("kind") == "preemption"]
    assert pre and Path(pre[0]["checkpoint"]).exists()
    kill_step = pre[0]["step"]

    resume = _spawn_cli_train(
        ckpt, jsonl, cli_workspace / "tokens.bin", kill_step + 6,
        extra=("--resume", str(ckpt)),
    )
    out2, _ = resume.communicate(timeout=240)
    assert resume.returncode == 0, out2
    summary = json.loads(out2.strip().splitlines()[-1])
    assert summary["steps"] == kill_step + 6


@pytest.mark.slow
def test_supervisor_end_to_end_kill_and_resume(cli_workspace):
    """ACCEPTANCE (d), process level: BT_FAULTS SIGKILLs the first child at
    step 12; the supervisor respawns with auto-resume (once_dir marker
    keeps the fault from re-firing) and the run completes."""
    ckpt = cli_workspace / "ckpt"
    once = cli_workspace / "once"
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "BT_FAULTS": json.dumps(
            {"kill_at_step": 12, "once_dir": str(once)}
        ),
    }
    proc = subprocess.run(
        [
            sys.executable, "-m", "bpe_transformer_tpu.training.cli",
            "train", "--supervise",
            "--data", str(cli_workspace / "tokens.bin"),
            "--model-config", str(cli_workspace / "model.json"),
            "--steps", "16", "--batch-size", "4",
            "--log-every", "2", "--eval-every", "1000",
            "--checkpoint-every", "10",
            "--checkpoint-dir", str(ckpt),
            "--warmup", "2",
            "--max-restarts", "3", "--restart-backoff", "0.1",
        ],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert (once / "kill.fired").exists()
    summary = json.loads((ckpt / "summary.json").read_text())
    assert summary["history"][-1]["step"] == 16
    assert load_checkpoint(ckpt / "latest.ckpt")["iteration"] == 16


# --------------------------------------- serving fault hooks (ISSUE 20)


def test_serving_fault_hooks_fire_once_with_cross_process_markers(
    tmp_path, monkeypatch
):
    """The fleet-chaos hooks: HTTP blackhole/delay and payload corruption
    each fire exactly once, the path filter scopes them, and the once_dir
    markers make "once" hold across a supervisor respawn (a fresh
    injector in a fresh process must NOT re-fire)."""
    plan = {
        "http_blackhole": True,
        "http_delay_s": 0.01,
        "http_fault_path": "/kv/import",
        "corrupt_payload": "truncate",
        "once_dir": str(tmp_path / "markers"),
    }
    monkeypatch.setenv("BT_FAULTS", json.dumps(plan))
    injector = FaultInjector.from_env()
    assert injector.active

    # Path filter: only the targeted endpoint is faulted.
    assert injector.on_http_request("/generate") is None
    assert injector.on_http_request("/kv/import") == "blackhole"
    # Blackhole spent; the delay fires (once) on the next matching hit.
    t0 = time.monotonic()
    assert injector.on_http_request("/kv/import") is None
    assert time.monotonic() - t0 >= 0.01
    assert injector.on_http_request("/kv/import") is None

    data = bytes(range(256)) * 4
    mangled = injector.on_export_payload(data)
    assert mangled == data[: len(data) // 2]  # truncate mode, fires once
    assert injector.on_export_payload(data) == data

    # A respawned process builds a FRESH injector from the same env: the
    # markers on disk keep every fired fault fired.
    respawned = FaultInjector.from_env()
    assert respawned.on_http_request("/kv/import") is None
    assert respawned.on_export_payload(data) == data
    for marker in ("http_blackhole", "http_delay", "corrupt_payload"):
        assert (tmp_path / "markers" / f"{marker}.fired").exists()


def test_serving_fault_bitflip_and_decode_tick_kill(monkeypatch):
    """The flip corruption lands one bit in the trailing quarter (the
    array section — the case only the wire CRC catches), and the
    mid-decode kill hook SIGKILLs at its tick exactly once."""
    injector = FaultInjector(FaultPlan(corrupt_payload="flip"))
    data = bytes(range(256))
    flipped = injector.on_export_payload(data)
    assert len(flipped) == len(data)
    diffs = [i for i, (a, b) in enumerate(zip(data, flipped)) if a != b]
    assert diffs == [(len(data) * 3) // 4]
    assert injector.on_export_payload(data) == data  # spent

    kills: list = []
    monkeypatch.setattr(
        "bpe_transformer_tpu.resilience.faults.os.kill",
        lambda pid, sig: kills.append((pid, sig)),
    )
    injector = FaultInjector(FaultPlan(kill_at_decode_tick=5))
    for tick in range(1, 5):
        injector.at_decode_tick(tick)
    assert kills == []
    injector.at_decode_tick(5)
    assert kills == [(os.getpid(), signal.SIGKILL)]
    injector.at_decode_tick(6)  # fired once; a respawn survives its tick
    assert len(kills) == 1

    # An idle injector (no plan) is inert on every serving hook.
    idle = FaultInjector(None)
    assert not idle.active
    idle.at_decode_tick(99)
    assert idle.on_http_request("/kv/import") is None
    assert idle.on_export_payload(b"x") == b"x"

    # Unknown plan fields fail loudly at parse time, not mid-incident.
    with pytest.raises(ValueError, match="unknown fault plan"):
        FaultPlan.from_json('{"http_blackhol": true}')
