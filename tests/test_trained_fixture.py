"""The trained-weights test family, on a regenerated fixture.

The reference pins trained-model behavior through its ``ts_state_dict``
fixture (`/root/reference/tests/conftest.py:194-202`), but its input
weights `ts_tests/model.pt` are a missing large blob — that family can
never be replayed from this mount.  These tests run the same KINDS of
checks against this repo's regenerated trained 3L/64d fixture
(tools/make_trained_fixture.py; exact `model_config.json` shape):

* trained weights round-trip through the torch-style state-dict schema and
  reproduce pinned forward logits;
* the same weights produce the same logits through the reference's adapter
  seam (``run_transformer_lm``), i.e. the trained-weights family runs
  through `compat/adapters.py` as the reference's `test_model.py` ran it;
* a 5-step AdamW trajectory continuing from the trained state is pinned
  (optimizer + schedule + clip on a REAL loss surface, not random init).
"""

from __future__ import annotations

from pathlib import Path

import numpy as np
import pytest

FIXTURE = Path(__file__).parent / "fixtures" / "trained_3l64d.npz"


@pytest.fixture(scope="module")
def fixture_arrays():
    if not FIXTURE.exists():
        pytest.skip("trained fixture missing; run tools/make_trained_fixture.py")
    with np.load(FIXTURE) as z:
        return {k: z[k] for k in z.files}


@pytest.fixture(scope="module")
def state_dict(fixture_arrays):
    return {
        k: v for k, v in fixture_arrays.items() if not k.startswith("pin/")
    }


def test_trained_forward_matches_pinned_logits(fixture_arrays, state_dict):
    import jax
    import jax.numpy as jnp

    from bpe_transformer_tpu.models import TS_TEST_CONFIG
    from bpe_transformer_tpu.models.transformer import forward, params_from_state_dict

    params = params_from_state_dict(state_dict, TS_TEST_CONFIG.num_layers)
    ids = jnp.asarray(fixture_arrays["pin/input_ids"])
    logits = jax.jit(lambda p, t: forward(p, t, TS_TEST_CONFIG))(params, ids)
    np.testing.assert_allclose(
        np.asarray(logits), fixture_arrays["pin/logits"], atol=1e-4, rtol=1e-4
    )


def test_trained_weights_through_adapter_seam(fixture_arrays, state_dict):
    """The reference's trained-weights path: torch state dict in,
    ``run_transformer_lm`` out (`/root/reference/tests/test_model.py:117-133`
    ran exactly this against its lost model.pt)."""
    import torch

    from bpe_transformer_tpu.compat.adapters import run_transformer_lm
    from bpe_transformer_tpu.models import TS_TEST_CONFIG as C

    weights = {k: torch.from_numpy(v.copy()) for k, v in state_dict.items()}
    logits = run_transformer_lm(
        vocab_size=C.vocab_size,
        context_length=C.context_length,
        d_model=C.d_model,
        num_layers=C.num_layers,
        num_heads=C.num_heads,
        d_ff=C.d_ff,
        rope_theta=C.rope_theta,
        weights=weights,
        in_indices=torch.from_numpy(fixture_arrays["pin/input_ids"].astype(np.int64)),
    )
    np.testing.assert_allclose(
        logits.detach().cpu().numpy(),
        fixture_arrays["pin/logits"],
        atol=1e-4,
        rtol=1e-4,
    )


def test_trained_adamw_trajectory_matches_pinned(fixture_arrays, state_dict):
    """5 AdamW steps from the trained state on seeded batches reproduce the
    pinned lm_head and loss curve — optimizer/schedule/clip pinned on a
    real loss surface."""
    import jax
    import jax.numpy as jnp

    from bpe_transformer_tpu.models import TS_TEST_CONFIG as C
    from bpe_transformer_tpu.models.transformer import params_from_state_dict
    from bpe_transformer_tpu.optim import adamw_init
    from bpe_transformer_tpu.training.train_step import TrainHParams, make_train_step

    tokens = np.load(
        Path(__file__).parent.parent / "benchmarks" / "northstar_tokens.npz"
    )["tokens"]
    params = params_from_state_dict(state_dict, C.num_layers)
    # The fixture generator's trajectory continues from the END-of-training
    # optimizer state being reset here would diverge — regenerate both sides
    # identically instead: the generator also starts its pinned trajectory
    # from a FRESH adamw_init at the trained params (see
    # tools/make_trained_fixture.py), so this is apples-to-apples.
    opt_state = adamw_init(params)
    step = make_train_step(C, TrainHParams())

    rng = np.random.default_rng(2)
    losses = []
    for _ in range(5):
        starts = rng.integers(0, len(tokens) - C.context_length - 1, size=32)
        x = np.stack([tokens[s : s + C.context_length] for s in starts])
        y = np.stack([tokens[s + 1 : s + C.context_length + 1] for s in starts])
        params, opt_state, m = step(
            params, opt_state, jnp.asarray(x.astype(np.int32)),
            jnp.asarray(y.astype(np.int32)),
        )
        losses.append(float(m["loss"]))

    np.testing.assert_allclose(
        losses, fixture_arrays["pin/traj_losses"], atol=1e-4, rtol=1e-4
    )
    np.testing.assert_allclose(
        np.asarray(params["lm_head"]),
        fixture_arrays["pin/traj_lm_head"],
        atol=1e-4,
        rtol=1e-4,
    )
