"""Certify the reference repo's own test files, run VERBATIM, in-suite.

`tools/run_reference_suite.py` stages every reference test file, conftest,
snapshot, and fixture byte-identical (symlinks into the read-only mount)
and swaps exactly one file — `tests/adapters.py`, the suite's designed
seam — for a re-export of `bpe_transformer_tpu.compat.adapters`.  This
test runs that staged suite as a subprocess and asserts the strongest
parity statement available: the reference's unmodified tests pass against
this framework.

Skipped tests inside the run are ONLY the missing-large-blob family
(`/root/reference/.MISSING_LARGE_BLOBS`), which the reference itself
cannot run from this mount; tests/test_trained_fixture.py covers that
family's test kinds on a regenerated fixture.
"""

from __future__ import annotations

import re
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
RUNNER = REPO / "tools" / "run_reference_suite.py"
REF_TESTS = Path("/root/reference/tests")


@pytest.mark.skipif(not REF_TESTS.exists(), reason="reference mount absent")
def test_reference_suite_passes_verbatim():
    proc = subprocess.run(
        [sys.executable, str(RUNNER)],
        capture_output=True,
        text=True,
        timeout=600,
    )
    tail = "\n".join((proc.stdout + proc.stderr).splitlines()[-15:])
    assert proc.returncode == 0, f"reference suite failed:\n{tail}"
    summary = re.search(r"(\d+) passed(?:, (\d+) skipped)?", proc.stdout)
    assert summary, f"no pytest summary found:\n{tail}"
    passed = int(summary.group(1))
    skipped = int(summary.group(2) or 0)  # a blob-restored mount has 0 skips
    # 48 collected as of the r4 mount: 36 runnable (all must pass — rc==0
    # already guarantees no failures) + 12 skipped missing-blob tests.  A
    # future mount with the blobs restored would only move skips to passes.
    assert passed >= 36, f"expected >=36 passing reference tests, got {passed}"
    assert passed + skipped >= 48, f"collection shrank: {passed}+{skipped} < 48"
