"""Self-healing fleet control plane (ISSUE 20): the crash-loop breaker,
stale-evidence observe-only degradation, per-(rule, target) hysteresis,
the pure decision rules, the actuator retry ladder, the replica
spawner/supervisor, and — behind the ``slow`` marker — the fleet chaos
end-to-end: two supervised subprocess replicas under kill-mid-decode +
blackholed ``/kv/import``, zero failed requests, token-identical
evacuations, and the respawned replica rejoining and receiving load.

The controller is jax-free; everything tier-1 here runs against stub
evidence, canned stdlib HTTP servers, and tiny ``python -c`` children.
"""

import json
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

import pytest

from bpe_transformer_tpu.serving.controller import (
    ActionBudget,
    FleetController,
    ReplicaSpawner,
    make_control_http_server,
    parse_spawn_slot,
)

pytestmark = pytest.mark.serving

REPO = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "fixtures"


# ------------------------------------------------------------- evidence


def _snap(url, *, online=True, queue=0, active=0, slots=2, kv_free=None,
          kv_total=None, role="both", draining=False, error=None):
    """One aggregator replica snapshot, shaped like FleetAggregator's."""
    return {
        "url": url, "online": online, "draining": draining, "role": role,
        "queue_depth": queue, "slots": slots, "active_slots": active,
        "kv_blocks_free": kv_free, "kv_blocks_total": kv_total,
        "error": error,
    }


def _evidence(snaps=(), *, t=100.0, time_unix=1000.0, alerts=(),
              queue_depth=0, active_slots=0, router=None):
    """A gathered-evidence dict: the aggregator /statusz page (last fleet
    record + per-replica sweep + alerts) plus an optional router page."""
    return {
        "fleet": {
            "fleet": {
                "kind": "fleet", "t": t, "time_unix": time_unix,
                "queue_depth": queue_depth, "active_slots": active_slots,
            },
            "replicas": list(snaps),
            "alerts": list(alerts),
        },
        "router": router,
        "errors": {},
    }


class _StubSpawner:
    """Duck-typed ReplicaSpawner for decide()/run_once() tests."""

    def __init__(self, idle=1, active=()):
        self._idle = idle
        self._active = list(active)
        self.spawned: list = []
        self.retired: list = []

    def idle(self):
        return self._idle

    def active(self):
        return list(self._active)

    def spawn(self):
        if self._idle <= 0:
            return None
        self._idle -= 1
        url = f"http://127.0.0.1:91{len(self.spawned):02d}"
        self.spawned.append(url)
        self._active.append(url)
        return url

    def retire(self, url=None):
        if not self._active:
            return None
        out = self._active.pop()
        self.retired.append(out)
        return out

    def snapshot(self):
        return [{"url": u, "live": True, "retiring": False, "restarts": 0}
                for u in self._active]

    def stop_all(self, timeout_s=30.0):
        pass


def _controller(**kw):
    kw.setdefault("wall_clock", lambda: 1000.0)
    kw.setdefault("sleep", lambda s: None)
    return FleetController("http://127.0.0.1:1", **kw)


# ---------------------------------------------------------------- budget


def test_action_budget_trips_and_never_auto_untrips():
    budget = ActionBudget(3)
    budget.note(False)
    budget.note(False)
    assert not budget.tripped and budget.consecutive == 2
    budget.note(True)  # real progress forgives
    assert budget.consecutive == 0
    for _ in range(3):
        budget.note(False)
    assert budget.tripped and budget.state == "tripped"
    budget.note(True)  # success after the trip does NOT re-arm
    assert budget.tripped and budget.state == "tripped"
    assert budget.total_failures == 5
    with pytest.raises(ValueError, match=">= 1"):
        ActionBudget(0)


def test_parse_spawn_slot():
    url, argv = parse_spawn_slot(
        "http://127.0.0.1:8091=python -m bpe_transformer_tpu.training.cli "
        "serve --port 8091 --evacuate-to 'http://a b'"
    )
    assert url == "http://127.0.0.1:8091"
    assert argv[:2] == ["python", "-m"]
    assert argv[-1] == "http://a b"  # shlex quoting survives
    for bad in ("no-equals", "=cmd only", "http://x=", "  =  "):
        with pytest.raises(ValueError, match="URL=CMD"):
            parse_spawn_slot(bad)


# ---------------------------------------------------------------- decide


def test_decide_rebalance_on_load_gap():
    ctl = _controller(rebalance_min_gap=3, rebalance_batch=2)
    hot = _snap("http://h", queue=5, active=2)
    cold = _snap("http://c", queue=0, active=1, slots=2)
    out = ctl.decide(_evidence([hot, cold]))
    assert [d["action"] for d in out] == ["rebalance"]
    assert out[0]["target"] == "http://h"
    assert out[0]["params"] == {"to": "http://c", "max_sessions": 2}
    assert "hold" not in out[0]

    # Below the gap: no decision (hysteresis against noise).
    calm = _snap("http://h", queue=1, active=1)
    assert ctl.decide(_evidence([calm, cold])) == []
    # A full cold peer cannot absorb the session.
    full = _snap("http://c", queue=0, active=2, slots=2)
    assert ctl.decide(_evidence([hot, full])) == []
    # Nothing in flight on the hot replica: nothing to move.
    queued_only = _snap("http://h", queue=9, active=0)
    assert ctl.decide(_evidence([queued_only, cold])) == []
    # Draining / prefill-role / offline replicas are not candidates.
    assert ctl.decide(_evidence([hot, _snap("http://c", draining=True)])) == []
    assert ctl.decide(_evidence([hot, _snap("http://c", role="prefill")])) == []
    assert ctl.decide(_evidence([hot])) == []


def test_decide_rebalance_on_kv_starvation():
    ctl = _controller(rebalance_min_gap=5, rebalance_headroom_frac=0.15)
    hot = _snap("http://h", queue=1, active=1, kv_free=1, kv_total=32)
    cold = _snap("http://c", queue=0, active=0, kv_free=30, kv_total=32)
    out = ctl.decide(_evidence([hot, cold]))
    assert len(out) == 1 and out[0]["action"] == "rebalance"
    assert "kv headroom" in out[0]["reason"]
    # Same load gap, but the cold peer is nearly as starved: hold off.
    tight = _snap("http://c", queue=0, active=0, kv_free=5, kv_total=32)
    assert ctl.decide(_evidence([hot, tight])) == []


def test_decide_partial_sweep_holds_rebalance_but_not_scaling():
    """An incomplete peer sweep (a declared replica the aggregator could
    not see) must downgrade load-comparing rules to observe-only while
    alert-driven scale-up still acts — a dead replica is exactly when
    capacity is needed."""
    spawner = _StubSpawner(idle=1)
    ctl = _controller(spawner=spawner, scale_sustain_s=10.0)
    snaps = [
        _snap("http://h", queue=6, active=2),
        _snap("http://c", queue=0, active=0),
        _snap("http://gone", online=False, error="connect refused"),
    ]
    alerts = [{"rule": "queue_growth", "since_t": 80.0}]
    out = ctl.decide(_evidence(snaps, t=100.0, alerts=alerts))
    by_action = {d["action"]: d for d in out}
    assert by_action["rebalance"]["hold"] == "partial_sweep"
    assert "hold" not in by_action["scale_up"]
    assert "queue_growth" in by_action["scale_up"]["reason"]


def test_decide_retune_follows_prompt_mix_with_hysteresis():
    ctl = _controller(retune_min_samples=16, retune_margin=0.25)

    def router_page(count=20, p75=48, threshold=8, prefill_available=True):
        return {
            "prompt_mix": {"count": count, "p75": p75},
            "prefill_threshold": threshold,
            "replicas": [
                {"role": "prefill", "available": prefill_available},
                {"role": "both", "available": True},
            ],
        }

    out = ctl.decide(_evidence(router=router_page()))
    assert [d["action"] for d in out] == ["retune"]
    assert out[0]["params"]["prefill_threshold"] == 48
    assert out[0]["target"] == "router"

    # Inside the hysteresis margin: no thrash.
    assert ctl.decide(_evidence(router=router_page(p75=50, threshold=48))) == []
    # Too few samples, no live prefill tier, or no router page: silent.
    assert ctl.decide(_evidence(router=router_page(count=3))) == []
    assert ctl.decide(
        _evidence(router=router_page(prefill_available=False))
    ) == []
    assert ctl.decide(_evidence(router=None)) == []
    # Degenerate mixes still produce a sane (>= 2) threshold.
    out = ctl.decide(_evidence(router=router_page(p75=1, threshold=None)))
    assert out[0]["params"]["prefill_threshold"] == 2


def test_decide_scale_up_sustained_and_scale_down_idle():
    clk = {"t": 0.0}
    spawner = _StubSpawner(idle=1, active=["http://spawned"])
    ctl = _controller(
        spawner=spawner, scale_sustain_s=10.0, scale_down_idle_s=50.0,
        clock=lambda: clk["t"],
    )
    # A young alert does not scale; a sustained one does.
    young = [{"rule": "queue_growth", "since_t": 95.0}]
    assert ctl.decide(_evidence(t=100.0, alerts=young, queue_depth=3)) == []
    old = [{"rule": "block_exhaustion", "since_t": 80.0}]
    out = ctl.decide(_evidence(t=100.0, alerts=old, queue_depth=3))
    assert [d["action"] for d in out] == ["scale_up"]
    # No idle slot left: nothing to spawn with.
    ctl2 = _controller(spawner=_StubSpawner(idle=0), scale_sustain_s=10.0)
    assert ctl2.decide(_evidence(t=100.0, alerts=old, queue_depth=3)) == []

    # Scale-down needs a LONG idle fleet; any work resets the timer.
    clk["t"] = 40.0
    assert ctl.decide(_evidence(queue_depth=1)) == []  # busy -> reset
    clk["t"] = 80.0
    assert ctl.decide(_evidence()) == []  # only 40s idle
    clk["t"] = 95.0
    out = ctl.decide(_evidence())
    assert [d["action"] for d in out] == ["scale_down"]
    assert out[0]["target"] == "http://spawned"


# -------------------------------------------------- run_once safety pins


def test_stale_evidence_holds_observe_only_and_edge_triggers():
    """ACCEPTANCE (ISSUE 20): stale fleet evidence degrades the
    controller to observe-only — one kind=control record saying why per
    hold EPISODE, not per tick — and fresh evidence re-arms it."""
    ctl = _controller(evidence_max_age_s=10.0)
    stale = _evidence(time_unix=900.0)  # wall clock is pinned at 1000
    fresh = _evidence(time_unix=1000.0)
    ctl.gather = lambda: stale

    records = ctl.run_once()
    assert len(records) == 1
    rec = records[0]
    assert rec["kind"] == "control" and rec["action"] == "hold"
    assert rec["outcome"] == "held"
    assert rec["reason"].startswith("stale_evidence")
    assert ctl.run_once() == []  # same episode: silent
    assert ctl.run_once() == []

    ctl.gather = lambda: fresh
    assert ctl.run_once() == []  # healthy and quiet: no records at all
    ctl.gather = lambda: stale
    assert len(ctl.run_once()) == 1  # a NEW episode records again

    # An unreachable aggregator is its own hold reason (new episode).
    ctl.gather = lambda: {"fleet": None, "router": None,
                          "errors": {"fleet": "connect refused"}}
    records = ctl.run_once()
    assert len(records) == 1
    assert records[0]["reason"].startswith("fleet_unreachable")
    assert ctl.statusz()["holds"] == 5


def test_breaker_trips_after_consecutive_failures_and_halts():
    """ACCEPTANCE (ISSUE 20): max_consecutive_failures failed actions
    without one success trip the crash-loop breaker; the controller then
    stops calling actuators entirely (observe-only until restarted)."""
    ctl = _controller(cooldown_s=0.0, max_consecutive_failures=2)
    ctl.gather = lambda: _evidence()
    decision = {"action": "rebalance", "target": "http://h",
                "reason": "load gap", "params": {"to": "http://c",
                                                 "max_sessions": 1}}
    ctl.decide = lambda ev: [dict(decision)]
    calls = []

    def failing_execute(d):
        calls.append(d)
        return {"ok": False, "attempts": 3, "detail": "HTTP 503: b'nope'"}

    ctl._execute = failing_execute

    first = ctl.run_once()
    assert [r["outcome"] for r in first] == ["failed"]
    assert first[0]["breaker"] == "closed" and not ctl.budget.tripped

    second = ctl.run_once()
    assert [r["action"] for r in second] == ["rebalance", "hold"]
    assert second[0]["outcome"] == "failed"
    assert second[1]["reason"].startswith("breaker_tripped")
    assert ctl.budget.tripped

    # Halted: no more actuator calls, and the hold is edge-triggered.
    assert ctl.run_once() == []
    assert ctl.run_once() == []
    assert len(calls) == 2
    page = ctl.statusz()
    assert page["breaker"] == "tripped"
    assert page["actions_failed"] == 2 and page["actions_ok"] == 0


def test_cooldown_hysteresis_observe_only_and_partial_hold_records():
    # Cooldown: the same (action, target) cannot refire inside the window.
    clk = {"t": 0.0}
    ctl = _controller(cooldown_s=100.0, clock=lambda: clk["t"])
    ctl.gather = lambda: _evidence()
    decision = {"action": "rebalance", "target": "http://h",
                "reason": "gap", "params": {"to": "http://c",
                                            "max_sessions": 1}}
    ctl.decide = lambda ev: [dict(decision)]
    ctl._execute = lambda d: {"ok": True, "attempts": 1,
                              "detail": {"moved": 1}}
    ok = ctl.run_once()
    assert [r["outcome"] for r in ok] == ["ok"]
    assert ok[0]["detail"] == {"moved": 1} and ok[0]["attempts"] == 1
    assert ctl.run_once() == []  # cooling
    assert ctl.statusz()["cooldown_skips"] == 1
    clk["t"] = 101.0
    assert [r["outcome"] for r in ctl.run_once()] == ["ok"]

    # observe_only mode records the decision and never touches actuators.
    obs = _controller(observe_only=True)
    obs.gather = lambda: _evidence()
    obs.decide = lambda ev: [dict(decision)]
    obs._execute = lambda d: pytest.fail("observe-only must not act")
    records = obs.run_once()
    assert [r["outcome"] for r in records] == ["observe_only"]

    # A rule-level hold (partial sweep) is observe-only with the cause.
    held = _controller()
    held.gather = lambda: _evidence()
    held.decide = lambda ev: [dict(decision, hold="partial_sweep")]
    held._execute = lambda d: pytest.fail("held decision must not act")
    records = held.run_once()
    assert records[0]["outcome"] == "observe_only"
    assert records[0]["held_because"] == "partial_sweep"


# ------------------------------------------------------------- actuators


class _Actuator:
    """A canned actuator endpoint: /admin/evacuate 503s twice then
    succeeds; /admin/threshold always 400s (semantic refusal)."""

    def __init__(self):
        self.evacuate_hits = 0
        self.threshold_hits = 0
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def do_POST(self):
                self.rfile.read(int(self.headers.get("Content-Length") or 0))
                if self.path == "/admin/evacuate":
                    outer.evacuate_hits += 1
                    if outer.evacuate_hits < 3:
                        return self.send_error(503, "draining")
                    body = json.dumps({"moved": 1}).encode()
                elif self.path == "/admin/threshold":
                    outer.threshold_hits += 1
                    return self.send_error(400, "threshold must be >= 1")
                else:
                    return self.send_error(404)
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.url = f"http://127.0.0.1:{self.server.server_address[1]}"
        self.thread = threading.Thread(
            target=self.server.serve_forever, daemon=True
        )
        self.thread.start()

    def close(self):
        self.server.shutdown()
        self.server.server_close()
        self.thread.join(timeout=10)


def test_execute_retries_transient_failures_and_breaks_on_4xx():
    actuator = _Actuator()
    try:
        ctl = _controller(
            router_url=actuator.url, action_retries=3,
            action_backoff_s=0.0, action_timeout_s=10.0,
        )
        # Two 503s, then success: the retry ladder absorbs the transient.
        result = ctl._execute({
            "action": "rebalance", "target": actuator.url,
            "reason": "gap",
            "params": {"to": "http://c", "max_sessions": 1},
        })
        assert result["ok"] and result["attempts"] == 3
        assert result["detail"] == {"moved": 1}
        assert actuator.evacuate_hits == 3

        # A 4xx is permanent: exactly one attempt, no retry hammering.
        result = ctl._execute({
            "action": "retune", "target": "router", "reason": "mix",
            "params": {"prefill_threshold": 0},
        })
        assert not result["ok"]
        assert result["detail"].startswith("HTTP 400")
        assert actuator.threshold_hits == 1

        # A dead actuator burns the bounded retries, then reports.
        dead = _controller(action_retries=2, action_backoff_s=0.0,
                           action_timeout_s=1.0)
        result = dead._execute({
            "action": "rebalance", "target": "http://127.0.0.1:9",
            "reason": "gap",
            "params": {"to": "http://c", "max_sessions": 1},
        })
        assert not result["ok"] and result["attempts"] == 2
    finally:
        actuator.close()


# --------------------------------------------------------------- spawner


def _wait_until(predicate, timeout_s=30.0, interval_s=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return predicate()


def test_replica_spawner_spawn_retire_and_crash_respawn_budget():
    sleeper = [sys.executable, "-c", "import time; time.sleep(600)"]
    crasher = [sys.executable, "-c", "import sys; sys.exit(3)"]
    spawner = ReplicaSpawner(
        [("http://127.0.0.1:9001", sleeper),
         ("http://127.0.0.1:9002/", crasher)],
        max_restarts=2, backoff_s=0.01, backoff_max_s=0.02,
        log=lambda *a: None,
    )
    try:
        assert spawner.idle() == 2 and spawner.active() == []
        assert spawner.spawn() == "http://127.0.0.1:9001"
        assert spawner.active() == ["http://127.0.0.1:9001"]
        assert spawner.spawn() == "http://127.0.0.1:9002"  # URL canonical
        assert spawner.spawn() is None  # every slot live

        # The crasher is respawned with backoff until the restart budget
        # is spent, then the slot is released (idle again, not undead).
        assert _wait_until(lambda: spawner.idle() == 1), spawner.snapshot()
        crashed = next(
            s for s in spawner.snapshot()
            if s["url"] == "http://127.0.0.1:9002"
        )
        assert not crashed["live"]
        assert crashed["restarts"] == 3  # max_restarts=2 exceeded

        # Retire SIGTERMs the newest live replica; supervision ends
        # cleanly instead of respawning it.
        assert spawner.retire() == "http://127.0.0.1:9001"
        assert _wait_until(lambda: spawner.idle() == 2), spawner.snapshot()
        assert spawner.retire() is None
    finally:
        spawner.stop_all(timeout_s=10.0)


# ----------------------------------------------------------- HTTP front


def test_control_http_server_statusz_and_healthz():
    ctl = _controller(max_consecutive_failures=1)
    server = make_control_http_server(ctl, port=0)
    port = server.server_address[1]
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        base = f"http://127.0.0.1:{port}"
        page = json.loads(
            urllib.request.urlopen(f"{base}/statusz", timeout=10).read()
        )
        assert page["breaker"] == "closed" and page["ticks"] == 0
        assert page["fleet_url"] == "http://127.0.0.1:1"
        health = json.loads(
            urllib.request.urlopen(f"{base}/healthz", timeout=10).read()
        )
        assert health["ok"] is True
        ring = json.loads(
            urllib.request.urlopen(
                f"{base}/debug/flightrecorder", timeout=10
            ).read()
        )
        assert "events" in ring

        ctl.budget.note(False)  # trips at max_consecutive_failures=1
        health = json.loads(
            urllib.request.urlopen(f"{base}/healthz", timeout=10).read()
        )
        assert health["ok"] is False and health["breaker"] == "tripped"
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{base}/nope", timeout=10)
        assert err.value.code == 404
    finally:
        server.shutdown()
        server.server_close()
        thread.join(timeout=10)


# ---------------------------------------------------- telemetry fixture


def test_control_records_through_report_and_monitor():
    """The kind=control stream folds into `bpe-tpu report` (== control ==
    section + COMPARE_METRICS) and the live monitor: the pinned fixture
    keeps the schema honest across sessions."""
    from bpe_transformer_tpu.telemetry.monitor import (
        fold_records,
        render_frame,
    )
    from bpe_transformer_tpu.telemetry.report import (
        extract_compare_metrics,
        summarize,
    )

    records = [
        json.loads(line)
        for line in (FIXTURES / "control_tiny.jsonl").read_text().splitlines()
        if line.strip()
    ]
    summary = summarize(records)
    control = summary["control"]
    assert control["n"] == 9
    assert control["actions_ok"] == 4
    assert control["actions_failed"] == 2
    assert control["observe_only"] == 1
    assert control["holds"] == 2
    assert control["hold_reasons"] == {"stale_evidence": 1,
                                       "breaker_tripped": 1}
    assert control["breaker_last"] == "tripped"
    assert control["breaker_tripped"] is True
    assert control["rebalance_p50_s"] == pytest.approx(0.42)
    assert control["rebalance_p99_s"] == pytest.approx(1.85)
    assert control["by_action"]["rebalance"] == 5
    assert any("breaker" in a for a in summary["anomalies"])

    metrics = extract_compare_metrics(summary)
    assert metrics["control_actions_failed"] == (2, "lower")
    assert metrics["rebalance_p99_s"] == (pytest.approx(1.85), "lower")

    state = fold_records(records)
    assert state["control_actions"] == 9
    assert state["control_failed"] == 2
    assert state["control_breaker"] == "tripped"
    assert state["anomalies"] == 2
    frame = render_frame(state, "control_tiny.jsonl")
    assert "ctrl" in frame and "breaker tripped" in frame


# ------------------------------------------------------- fleet chaos e2e


def _free_port() -> int:
    with socket.socket() as sock:
        sock.bind(("127.0.0.1", 0))
        return sock.getsockname()[1]


def _get_json(url, timeout=10):
    return json.loads(urllib.request.urlopen(url, timeout=timeout).read())


@pytest.mark.slow
def test_fleet_chaos_kill_blackhole_respawn_e2e(tmp_path):
    """ACCEPTANCE (ISSUE 20): a two-replica controller-supervised fleet
    under kill-mid-decode + blackholed /kv/import serves every request —
    zero failures, the router replays the dead replica's work, the
    supervisor respawns it and the router's suspect probe readmits it —
    and a controller-driven rebalance whose first import is blackholed
    retries under one idempotency key, grafting each evacuated session
    exactly once, token-identical to the monolithic reference."""
    import dataclasses
    import pickle

    import jax
    import numpy as np

    from bpe_transformer_tpu.checkpointing import save_checkpoint
    from bpe_transformer_tpu.models import TS_TEST_CONFIG, init_params
    from bpe_transformer_tpu.serving import ServingEngine
    from bpe_transformer_tpu.serving.router import Router

    cfg = dataclasses.replace(
        TS_TEST_CONFIG, vocab_size=128, context_length=64
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(7)
    prompts = [
        [int(t) for t in rng.integers(0, cfg.vocab_size, size=6)]
        for _ in range(8)
    ]
    # Monolithic greedy reference: what every request must produce no
    # matter how many replicas, kills, or migrations it crossed. The serve
    # subprocesses stop on the tokenizer's appended special token (id 127,
    # cmd_serve's default_stop_id), so the reference must too.
    ref = {}
    with ServingEngine(
        params, cfg, slots=2, paged=True, block_size=8
    ) as mono:
        for i, prompt in enumerate(prompts):
            ref[i] = mono.generate(
                prompt, max_new_tokens=48, temperature=0.0, stop_id=127
            ).token_ids

    ckpt = tmp_path / "model.ckpt"
    save_checkpoint(
        ckpt, params=params,
        extra={"model_config": dataclasses.asdict(cfg)},
    )
    tok_dir = tmp_path / "tok"
    tok_dir.mkdir()
    with open(tok_dir / "vocab.pkl", "wb") as f:
        pickle.dump({i: bytes([i]) for i in range(127)}, f)
    with open(tok_dir / "merges.pkl", "wb") as f:
        pickle.dump([], f)

    port_a, port_b = _free_port(), _free_port()
    url_a = f"http://127.0.0.1:{port_a}"
    url_b = f"http://127.0.0.1:{port_b}"
    once_dir = tmp_path / "faults"
    faults = json.dumps({
        "kill_at_decode_tick": 20,
        "http_blackhole": True,
        "http_fault_path": "/kv/import",
        "once_dir": str(once_dir),
    })

    def serve_argv(port, *extra_env, evacuate_to):
        return [
            "env", f"PYTHONPATH={REPO}", "JAX_PLATFORMS=cpu", *extra_env,
            sys.executable, "-m", "bpe_transformer_tpu.training.cli",
            "serve",
            "--checkpoint", str(ckpt), "--tokenizer-dir", str(tok_dir),
            "--host", "127.0.0.1", "--port", str(port), "--slots", "2",
            "--paged", "--block-size", "8", "--drain-timeout", "60",
            "--evacuate-to", evacuate_to,
        ]

    spawner = ReplicaSpawner(
        [
            (url_a, serve_argv(port_a, f"BT_FAULTS={faults}",
                               evacuate_to=url_b)),
            (url_b, serve_argv(port_b, evacuate_to=url_a)),
        ],
        max_restarts=3, backoff_s=0.5,
    )
    router = Router(
        [url_a, url_b], poll_interval_s=0.3, suspect_after=2,
        probe_backoff_s=0.3, probe_backoff_max_s=2.0,
    )
    results: dict = {}
    errors: list = []

    def fire(i, base_url=None):
        body = json.dumps({
            "prompt_ids": prompts[i], "max_new_tokens": 48,
            "temperature": 0.0,
        }).encode()
        try:
            if base_url is None:
                code, payload = router.handle_generate(body)
                assert code == 200, payload
            else:
                req = urllib.request.Request(
                    f"{base_url}/generate", data=body,
                    headers={"Content-Type": "application/json"},
                )
                payload = json.loads(
                    urllib.request.urlopen(req, timeout=300).read()
                )
            results[i] = payload
        except Exception as exc:  # noqa: BLE001 — the assertion is "none"
            errors.append((i, repr(exc)))

    try:
        assert spawner.spawn() == url_a
        assert spawner.spawn() == url_b
        router.start()
        assert _wait_until(
            lambda: router.statusz()["available"] == 2, timeout_s=300,
            interval_s=0.5,
        ), "replicas never came up"

        # ---- phase 1: kill replica A mid-decode under threaded load.
        # Its 20th decode tick SIGKILLs it; the router replays the dead
        # connections on B and quarantines A; the spawner respawns A.
        threads = [
            threading.Thread(target=fire, args=(i,)) for i in range(6)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not errors, errors
        assert (once_dir / "kill_decode.fired").exists(), (
            "the mid-decode kill never fired — phase 1 proved nothing"
        )
        for i in range(6):
            assert tuple(results[i]["token_ids"]) == ref[i], (
                f"request {i} diverged across the kill/replay"
            )

        # The respawned A rejoins through the suspect probe path.
        assert _wait_until(
            lambda: router.statusz()["available"] == 2, timeout_s=300,
            interval_s=0.5,
        ), f"replica A never rejoined: {router.statusz()}"
        page = router.statusz()
        assert page["suspected_total"] >= 1
        assert page["recoveries_total"] >= 1

        # ---- phase 2: controller-driven rebalance B -> A with the first
        # /kv/import blackholed.  The relay must retry under ONE
        # idempotency key; the respawned A grafts each session once.
        imports_before = _get_json(f"{url_a}/statusz")["migrations_in"]
        ctl = FleetController(
            "http://127.0.0.1:1", spawner=spawner,
            action_timeout_s=120.0, action_retries=3, action_backoff_s=0.5,
        )
        moved = 0
        for _ in range(3):  # sessions must be mid-flight to move
            # Fire each request twice (4 sessions, 2 slots): the queue
            # keeps B's slots occupied long enough that the evacuate —
            # triggered the moment /statusz shows a live session, not
            # after a blind sleep — catches one mid-decode even on a
            # warm engine where a full generation takes well under a
            # second.
            threads = [
                threading.Thread(target=fire, args=(i, url_b))
                for i in (6, 7, 6, 7)
            ]
            for t in threads:
                t.start()
            assert _wait_until(
                lambda: _get_json(f"{url_b}/statusz")["active_slots"] > 0,
                timeout_s=60, interval_s=0.02,
            ), "requests never reached a decode slot on B"
            result = ctl._execute({
                "action": "rebalance", "target": url_b,
                "reason": "fleet chaos e2e",
                "params": {"to": url_a, "max_sessions": 2},
            })
            assert result["ok"], result
            for t in threads:
                t.join(timeout=300)
            assert not errors, errors
            for i in range(6, 8):
                assert tuple(results[i]["token_ids"]) == ref[i], (
                    f"request {i} diverged across the evacuation"
                )
            moved = result["detail"]["moved"]
            if moved:
                break
        assert moved >= 1, "no session was ever mid-flight to evacuate"
        imports_after = _get_json(f"{url_a}/statusz")["migrations_in"]
        # Exactly once per moved session: the blackholed first attempt
        # plus its retry graft ONE session, not two.
        assert imports_after - imports_before == moved
        assert (once_dir / "http_blackhole.fired").exists(), (
            "the import blackhole never fired — the retry path was idle"
        )
    finally:
        router.close()
        spawner.stop_all(timeout_s=60.0)
