"""The reference-compatibility seam, exercised the way the reference's
CS336-derived suite drives it: torch tensors in, torch tensors out."""

import numpy as np
import pytest
import torch
import torch.nn as nn
import torch.nn.functional as F

from bpe_transformer_tpu.compat import (
    get_adamw_cls,
    get_tokenizer,
    run_cross_entropy,
    run_get_batch,
    run_get_lr_cosine_schedule,
    run_gradient_clipping,
    run_linear,
    run_load_checkpoint,
    run_rope,
    run_save_checkpoint,
    run_scaled_dot_product_attention,
    run_silu,
    run_softmax,
    run_swiglu,
    run_train_bpe,
    run_transformer_block,
    run_transformer_lm,
)
from tests.test_model import CFG, random_state_dict, torch_block, torch_lm


def test_linear_adapter():
    w = torch.randn(16, 8)
    x = torch.randn(3, 5, 8)
    np.testing.assert_allclose(
        run_linear(8, 16, w, x).numpy(), (x @ w.T).numpy(), atol=1e-5
    )


def test_silu_softmax_adapters():
    # Seeded: the softmax(x + 100) overflow check compares against
    # softmax(x) at atol 1e-6, and f32 rounding of `x + 100` can exceed
    # that for unlucky unseeded draws with |x| large.
    torch.manual_seed(0)
    x = torch.randn(4, 7)
    np.testing.assert_allclose(
        run_silu(x).numpy(), F.silu(x).numpy(), atol=1e-6
    )
    np.testing.assert_allclose(
        run_softmax(x + 100, dim=-1).numpy(), F.softmax(x, dim=-1).numpy(), atol=1e-6
    )


def test_sdpa_adapter_matches_reference_snapshot(reference_snapshots):
    expected = dict(
        np.load(reference_snapshots / "test_scaled_dot_product_attention.npz")
    )["array"]
    torch.manual_seed(1)
    q = torch.randn(4, 12, 64)
    torch.manual_seed(2)
    k = torch.randn(4, 16, 64)
    torch.manual_seed(3)
    v = torch.randn(4, 16, 64)
    torch.manual_seed(5)
    mask = torch.randn(4, 12, 16) > 0.5
    out = run_scaled_dot_product_attention(q, k, v, mask)
    np.testing.assert_allclose(out.numpy(), expected, atol=1e-6, rtol=1e-4)


def test_4d_sdpa_adapter_matches_reference_snapshot(reference_snapshots):
    """Replays `test_4d_scaled_dot_product_attention.npz` with the seeded
    fixtures of `/root/reference/tests/test_model.py:65-74` (the (batch*head)
    leading dim split into (batch=2, head=2))."""
    expected = dict(
        np.load(reference_snapshots / "test_4d_scaled_dot_product_attention.npz")
    )["array"]
    torch.manual_seed(1)
    q = torch.randn(4, 12, 64)
    torch.manual_seed(2)
    k = torch.randn(4, 16, 64)
    torch.manual_seed(3)
    v = torch.randn(4, 16, 64)
    torch.manual_seed(5)
    mask = torch.randn(4, 12, 16) > 0.5
    q, k, v = (t.reshape(2, 2, *t.shape[1:]) for t in (q, k, v))
    mask = mask.reshape(2, 2, 12, 16)
    out = run_scaled_dot_product_attention(q, k, v, mask)
    np.testing.assert_allclose(out.numpy(), expected, atol=1e-6, rtol=1e-4)


def test_rope_adapter_matches_reference_snapshot(reference_snapshots):
    expected = dict(np.load(reference_snapshots / "test_rope.npz"))["array"]
    torch.manual_seed(4)
    x = torch.randn(4, 12, 64)
    out = run_rope(64, 10000.0, 12, x, torch.arange(12))
    np.testing.assert_allclose(out.numpy(), expected, atol=1e-6, rtol=1e-4)


def test_swiglu_adapter():
    d, ff = 64, 128
    torch.manual_seed(0)
    w1, w3 = torch.randn(ff, d) * 0.1, torch.randn(ff, d) * 0.1
    w2 = torch.randn(d, ff) * 0.1
    x = torch.randn(2, 5, d)
    expected = (F.silu(x @ w1.T) * (x @ w3.T)) @ w2.T
    np.testing.assert_allclose(
        run_swiglu(d, ff, w1, w2, w3, x).numpy(), expected.numpy(), atol=1e-5
    )


def test_transformer_block_adapter_matches_oracle():
    sd = random_state_dict(CFG)
    block_weights = {
        k[len("layers.0."):]: v for k, v in sd.items() if k.startswith("layers.0.")
    }
    torch.manual_seed(7)
    x = torch.randn(4, 12, CFG.d_model)
    expected = torch_block(x, block_weights, CFG.num_heads, CFG.rope_theta)
    out = run_transformer_block(
        CFG.d_model, CFG.num_heads, CFG.d_ff, 16, CFG.rope_theta, block_weights, x
    )
    np.testing.assert_allclose(out.numpy(), expected.numpy(), atol=2e-5, rtol=1e-4)


def test_transformer_lm_adapter_matches_oracle():
    sd = random_state_dict(CFG)
    torch.manual_seed(42)
    indices = torch.randint(0, CFG.vocab_size, (4, 12))
    expected = torch_lm(indices, sd, CFG)
    out = run_transformer_lm(
        CFG.vocab_size, 16, CFG.d_model, CFG.num_layers, CFG.num_heads,
        CFG.d_ff, CFG.rope_theta, sd, indices,
    )
    np.testing.assert_allclose(out.numpy(), expected.numpy(), atol=1e-4, rtol=1e-2)


def test_cross_entropy_adapter():
    logits = torch.rand(8, 5) * 1000
    targets = torch.randint(0, 5, (8,))
    expected = F.cross_entropy(logits, targets)
    np.testing.assert_allclose(
        run_cross_entropy(logits, targets).numpy(), expected.numpy(), atol=1e-4
    )


def test_gradient_clipping_adapter_in_place():
    torch.manual_seed(0)
    tensors = [torch.randn(5, 5) for _ in range(3)]
    max_norm = 1e-2

    ours = tuple(nn.Parameter(t.clone()) for t in tensors)
    ours[-1].requires_grad_(False)
    torch.cat([p for p in ours]).sum().backward()
    run_gradient_clipping(ours, max_norm)

    theirs = tuple(nn.Parameter(t.clone()) for t in tensors)
    theirs[-1].requires_grad_(False)
    torch.cat([p for p in theirs]).sum().backward()
    torch.nn.utils.clip_grad_norm_(theirs, max_norm)

    for a, b in zip(ours, theirs):
        if a.grad is not None:
            np.testing.assert_allclose(a.grad.numpy(), b.grad.numpy(), atol=1e-6)


def _optimize(opt_class) -> torch.Tensor:
    """The reference's 1000-step optimizer trace (`test_optimizer.py:7-26`)."""
    torch.manual_seed(42)
    model = nn.Linear(3, 2, bias=False)
    opt = opt_class(
        model.parameters(), lr=1e-3, weight_decay=0.01, betas=(0.9, 0.999), eps=1e-8
    )
    for _ in range(1000):
        opt.zero_grad()
        x = torch.rand(model.in_features)
        y_hat = model(x)
        y = torch.tensor([x[0] + x[1], -x[2]])
        ((y - y_hat) ** 2).sum().backward()
        opt.step()
    return model.weight.detach()


@pytest.mark.slow
def test_adamw_cls_matches_torch():
    expected = _optimize(torch.optim.AdamW)
    actual = _optimize(get_adamw_cls())
    assert torch.allclose(actual, expected, atol=1e-4)


def test_adamw_matches_torch_or_reference_snapshot(reference_snapshots):
    """Replays `test_adamw.npz` with the reference's equivalence-class
    semantics (`/root/reference/tests/test_optimizer.py:29-49`): the 1000-step
    trace must match torch AdamW *or* the pinned reference weights (the two
    differ in weight-decay application order at float32 resolution)."""
    actual = _optimize(get_adamw_cls())
    pytorch_weights = _optimize(torch.optim.AdamW)
    if torch.allclose(actual, pytorch_weights, atol=1e-4):
        return
    expected = dict(np.load(reference_snapshots / "test_adamw.npz"))["array"]
    np.testing.assert_allclose(actual.numpy(), expected, atol=1e-4)


def test_lr_schedule_adapter():
    assert run_get_lr_cosine_schedule(0, 1.0, 0.1, 7, 21) == 0
    assert run_get_lr_cosine_schedule(7, 1.0, 0.1, 7, 21) == 1.0
    assert run_get_lr_cosine_schedule(24, 1.0, 0.1, 7, 21) == 0.1


def test_get_batch_adapter():
    dataset = np.arange(100)
    x, y = run_get_batch(dataset, 8, 7, "cpu")
    assert x.dtype == torch.int64
    assert x.shape == (8, 7)
    np.testing.assert_allclose((x + 1).numpy(), y.numpy())
    with pytest.raises((RuntimeError, AssertionError)):
        run_get_batch(dataset, 8, 7, "cuda:99")


class _Net(nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(20, 30)
        self.fc2 = nn.Linear(30, 5)

    def forward(self, x):
        return self.fc2(F.relu(self.fc1(x)))


def test_checkpoint_adapter_roundtrip(tmp_path):
    torch.manual_seed(0)
    model = _Net()
    opt = get_adamw_cls()(model.parameters(), lr=1e-3, weight_decay=0.01,
                          betas=(0.9, 0.999), eps=1e-8)
    for _ in range(5):
        opt.zero_grad()
        ((model(torch.rand(20)) - torch.rand(5)) ** 2).sum().backward()
        opt.step()

    path = tmp_path / "ckpt.pt"
    run_save_checkpoint(model, opt, iteration=5, out=path)

    fresh_model = _Net()
    fresh_opt = get_adamw_cls()(fresh_model.parameters(), lr=1e-3,
                                weight_decay=0.01, betas=(0.9, 0.999), eps=1e-8)
    assert run_load_checkpoint(path, fresh_model, fresh_opt) == 5

    for key, value in model.state_dict().items():
        np.testing.assert_allclose(
            value.numpy(), fresh_model.state_dict()[key].numpy()
        )
    # Optimizer internal state must also roundtrip (moments, step counts).
    orig_state = opt.state_dict()["state"]
    new_state = fresh_opt.state_dict()["state"]
    assert set(orig_state.keys()) == set(new_state.keys())
    for k in orig_state:
        for sub, val in orig_state[k].items():
            np.testing.assert_allclose(
                np.asarray(val), np.asarray(new_state[k][sub])
            )
    # And training must continue identically after the restore.
    torch.manual_seed(1)
    x, y = torch.rand(20), torch.rand(5)
    for m, o in ((model, opt), (fresh_model, fresh_opt)):
        o.zero_grad()
        ((m(x) - y) ** 2).sum().backward()
        o.step()
    for key, value in model.state_dict().items():
        np.testing.assert_allclose(
            value.numpy(), fresh_model.state_dict()[key].numpy(), atol=1e-7
        )


def test_train_bpe_and_tokenizer_adapters(tiny_corpus):
    vocab, merges = run_train_bpe(tiny_corpus, 300, ["<|endoftext|>"])
    tok = get_tokenizer(vocab, merges, ["<|endoftext|>"])
    text = "the quick brown fox<|endoftext|>"
    assert tok.decode(tok.encode(text)) == text


def test_train_bpe_special_tokens_reference_snapshot(reference_snapshots):
    """Replays `test_train_bpe_special_tokens.pkl`
    (`/root/reference/tests/test_train_bpe.py:66-89`).  The snapshot itself
    is always validated; the full training replay needs the 5 MB corpus,
    which the mounted reference lists in `.MISSING_LARGE_BLOBS` — when a
    checkout supplies it, the parity assertion runs."""
    import pickle

    with open(reference_snapshots / "test_train_bpe_special_tokens.pkl", "rb") as f:
        expected = pickle.load(f)
    assert set(expected) >= {"vocab_keys", "vocab_values", "merges"}
    assert expected["vocab_keys"] == set(range(1000))
    assert b"<|endoftext|>" in expected["vocab_values"]
    assert len(expected["merges"]) == 1000 - 256 - 1  # byte vocab + special

    corpus = (
        reference_snapshots.parent / "fixtures" / "tinystories_sample_5M.txt"
    )
    if not corpus.is_file():
        pytest.skip("tinystories_sample_5M.txt absent (.MISSING_LARGE_BLOBS)")
    vocab, merges = run_train_bpe(corpus, 1000, ["<|endoftext|>"])
    for word in vocab.values():
        if word != b"<|endoftext|>":
            assert b"<|" not in word
    assert set(vocab.keys()) == expected["vocab_keys"]
    assert set(vocab.values()) == expected["vocab_values"]
    assert merges == expected["merges"]
