"""Flight recorder & incident forensics (ISSUE 16): the bounded decision
ring, triggered black-box dumps, size-based JSONL rotation, alert history,
and the `bpe-tpu incident` cross-replica postmortem bundler.

The correctness bar: recording is pure host-side bookkeeping (the
fetch-count test pins ZERO extra device syncs on the serving tick and the
training step with the ring enabled), dumps carry the parked/rejected
decisions that explain an alert, and the incident bundle's timeline is
wall-clock-ordered across hosts.
"""

import dataclasses
import json
import threading
import time
import urllib.request
from pathlib import Path

import numpy as np
import pytest

import jax

from bpe_transformer_tpu.models import ModelConfig, TS_TEST_CONFIG, init_params
from bpe_transformer_tpu.serving import Request, ServingEngine, make_http_server
from bpe_transformer_tpu.telemetry import (
    FlightRecorder,
    MetricsLogger,
    Telemetry,
    validate_record,
)
from bpe_transformer_tpu.telemetry.alerts import (
    AlertEngine,
    BlockExhaustionRule,
    QueueGrowthRule,
)
from bpe_transformer_tpu.telemetry.incident import main as incident_main
from bpe_transformer_tpu.telemetry.report import (
    extract_compare_metrics,
    load_records,
    render_report,
    summarize,
)

REPO = Path(__file__).resolve().parent.parent

CFG = dataclasses.replace(TS_TEST_CONFIG, vocab_size=128, context_length=32)

TINY_TRAIN = ModelConfig(
    vocab_size=128,
    context_length=16,
    d_model=32,
    num_layers=2,
    num_heads=2,
    d_ff=64,
)
TRAIN_HP = dict(
    max_learning_rate=1e-3,
    min_learning_rate=1e-4,
    warmup_iters=2,
    cosine_cycle_iters=50,
)


@pytest.fixture(scope="module")
def setup():
    params = init_params(jax.random.PRNGKey(0), CFG)
    rng = np.random.default_rng(0)
    prompts = [
        [int(t) for t in rng.integers(0, CFG.vocab_size, size=n)]
        for n in (3, 7, 12, 19)
    ]
    return params, prompts


# ----------------------------------------------------------------- the ring


def test_ring_bounds_coalesces_and_snapshots_are_copies():
    """Capacity is a hard memory cap (evictions counted, never an error),
    coalesce=True merges consecutive same-event/same-request chatter into
    one slot, and snapshot() hands out copies the caller can't corrupt."""
    clock = iter(float(i) for i in range(1000))
    rec = FlightRecorder("serve", capacity=4, clock=lambda: next(clock))
    with pytest.raises(ValueError, match="capacity"):
        FlightRecorder("serve", capacity=0)

    for i in range(6):
        rec.record("admit", request_id=f"r{i}", slot=i, none_field=None)
    assert rec.recorded == 6 and rec.dropped == 2
    events = rec.snapshot()
    assert [e["request_id"] for e in events] == ["r2", "r3", "r4", "r5"]
    assert all("none_field" not in e for e in events)  # nulls stripped
    assert all(e["time_unix"] > 0 for e in events)  # absolute stamps ride

    # Coalescing: 5 consecutive ticks occupy ONE slot with a count and the
    # first occurrence's run-relative timestamp preserved.
    for i in range(5):
        rec.record("tick", coalesce=True, n_events=i)
    events = rec.snapshot()
    assert [e["event"] for e in events] == ["admit", "admit", "admit", "tick"]
    tick = events[-1]
    assert tick["count"] == 5 and tick["n_events"] == 4
    assert tick["first_t"] < tick["t"]
    # A different request_id breaks the merge — per-request park retries
    # coalesce per request, not across requests.
    rec.record("park", coalesce=True, request_id="a")
    rec.record("park", coalesce=True, request_id="b")
    assert [e.get("request_id") for e in rec.snapshot()[-2:]] == ["a", "b"]

    # Snapshot copies: mutating the caller's view never touches the ring.
    rec.snapshot()[-1]["request_id"] = "corrupted"
    assert rec.snapshot()[-1]["request_id"] == "b"

    # try_record (the signal-handler path) appends without blocking; held
    # lock -> False and the event is dropped rather than deadlocking.
    assert rec.try_record("signal_received", signal="SIGTERM") is True
    assert rec.snapshot()[-1]["signal"] == "SIGTERM"
    with rec._lock:
        assert rec.try_record("signal_received") is False

    stats = rec.stats()
    assert stats["size"] == 4 and stats["capacity"] == 4
    assert stats["recorded"] == rec.recorded


def test_blackbox_cooldown_dedupes_storms_and_force_bypasses():
    """One incident, one dump: inside the cooldown blackbox() returns None
    (an alert storm re-firing every sample must not flood the stream);
    force=True (manual POST, terminal paths) always dumps.  Retained dumps
    are a bounded deque; context keys never clobber dump fields."""
    t = [0.0]
    rec = FlightRecorder(
        "serve", capacity=8, clock=lambda: t[0], dump_cooldown_s=30.0,
        max_dumps=2,
    )
    rec.record("park", request_id="r1", backlog=1)
    dump = rec.blackbox(
        "alert:block_exhaustion",
        context={"queue_depth": 9, "trigger": "IGNORED", "kvpool": {"x": 1}},
    )
    assert dump["kind"] == "blackbox" and dump["component"] == "serve"
    assert dump["trigger"] == "alert:block_exhaustion"  # context can't clobber
    assert dump["queue_depth"] == 9 and dump["kvpool"] == {"x": 1}
    assert [e["event"] for e in dump["events"]] == ["park"]
    assert validate_record(dump) == []

    t[0] = 10.0  # inside the 30s cooldown
    assert rec.blackbox("alert:block_exhaustion") is None
    forced = rec.blackbox("manual", force=True)
    assert forced is not None and forced["trigger"] == "manual"
    t[0] = 50.0  # 40s past the forced dump: cooldown expired again
    assert rec.blackbox("watchdog_hang") is not None

    dumps = rec.dumps()  # max_dumps=2: oldest dump evicted
    assert [d["trigger"] for d in dumps] == ["manual", "watchdog_hang"]
    assert rec.stats()["dumps"] == 2
    page = rec.debug_page()
    assert page["component"] == "serve" and len(page["dumps"]) == 2
    assert [e["event"] for e in page["events"]] == ["park"]


# ------------------------------------------------------- satellite: rotation


def test_metrics_logger_rotates_restamps_manifest_and_gcs_segments(tmp_path):
    """Size-based JSONL rotation: segments cut at record boundaries only
    (every line in every segment parses), the run manifest is re-stamped
    as the head of each new segment, and GC keeps the newest
    keep_segments — stranded segments from earlier runs included."""
    path = tmp_path / "metrics.jsonl"
    # A stranded segment from a previous run: GC must claim it too.
    (tmp_path / "metrics.jsonl.1").write_text(
        json.dumps({"kind": "manifest", "run_kind": "old"}) + "\n"
    )
    manifest = {"kind": "manifest", "run_kind": "serve", "host": "t"}
    logger = MetricsLogger(jsonl_path=path, max_bytes=200, keep_segments=2)
    logger.log(manifest)
    for i in range(30):
        logger.log({"kind": "event", "name": "tick", "t": float(i), "i": i})
    logger.close()

    segments = sorted(
        tmp_path.glob("metrics.jsonl.*"),
        key=lambda p: int(p.name.rsplit(".", 1)[1]),
    )
    assert 1 <= len(segments) <= 2, "GC must keep at most keep_segments"
    indices = [int(p.name.rsplit(".", 1)[1]) for p in segments]
    assert 1 not in indices, "stranded segment from the old run must be GC'd"

    seen: list[int] = []
    for segment in segments + [path]:
        lines = segment.read_text().splitlines()
        records = [json.loads(line) for line in lines]  # no torn records
        assert len(lines) >= 1
        # Every rotated-into segment leads with the re-stamped manifest, so
        # report's manifest resolution works on any retained segment alone.
        assert records[0]["kind"] == "manifest"
        assert records[0]["run_kind"] == "serve"
        seen.extend(r["i"] for r in records if r.get("kind") == "event")
    # Retained segments hold a contiguous, ordered tail of the stream.
    assert seen == sorted(seen) and seen[-1] == 29

    with pytest.raises(ValueError, match="max_bytes"):
        MetricsLogger(jsonl_path=tmp_path / "x.jsonl", max_bytes=0)


# -------------------------------------------------- satellite: alert history


def test_alert_engine_history_keeps_bounded_transitions():
    """AlertEngine retains the last N firing/cleared edges after they
    clear — active() alone forgets an incident the moment it ends."""
    engine = AlertEngine(
        [QueueGrowthRule(window=3, min_depth=4)], history_limit=4
    )
    t = 0.0
    for depth in (0, 4, 9):  # monotone growth to >= min_depth: fires
        engine.feed({"queue_depth": depth}, t)
        t += 1.0
    assert [a["rule"] for a in engine.active()] == ["queue_growth"]
    for depth in (9, 9, 9, 0):  # growth stops: clears
        engine.feed({"queue_depth": depth}, t)
        t += 1.0
    assert engine.active() == []

    history = engine.history()
    assert [(h["rule"], h["state"]) for h in history] == [
        ("queue_growth", "firing"),
        ("queue_growth", "cleared"),
    ]
    assert history[1]["active_s"] > 0
    assert engine.history(1)[0]["state"] == "cleared"

    # Bounded: 3 more fire/clear cycles overflow the 4-entry deque.
    for _ in range(3):
        for depth in (0, 4, 9, 9, 9, 9, 0):
            engine.feed({"queue_depth": depth}, t)
            t += 1.0
    assert len(engine.history()) == 4
    # History copies: callers can't corrupt the retained transitions.
    engine.history()[-1]["rule"] = "corrupted"
    assert engine.history()[-1]["rule"] == "queue_growth"


# ------------------------------------------- e2e: exhaustion -> dump -> ring


@pytest.mark.serving
def test_block_exhaustion_alert_flushes_blackbox_with_parked_admissions(
    setup,
):
    """ACCEPTANCE (offline, deterministic): a paged engine driven to KV
    block exhaustion parks the second admission, the block_exhaustion
    alert fires on the free==0 gauge sample, and the triggered
    kind="blackbox" dump's ring contains that parked admission — the
    forensic chain the flight recorder exists for."""
    params, prompts = setup
    records = []
    telemetry = Telemetry(sink=records.append)
    serving = ServingEngine(
        params, CFG, slots=2, min_bucket=8, paged=True, block_size=8,
        num_kv_blocks=5, prefix_cache=False, telemetry=telemetry,
        engine_record_every_s=0.0,
        # Pin the rule set: a compile-storm edge from this test's own cold
        # XLA programs must not race the exhaustion dump into the cooldown.
        alert_rules=[BlockExhaustionRule()],
    )
    serving._running = True  # drive the worker loop by hand
    h1 = serving.submit(
        Request(
            prompt_ids=tuple(prompts[2]), max_new_tokens=16, temperature=0.0,
        )
    )
    h2 = serving.submit(
        Request(
            prompt_ids=tuple(prompts[3]), max_new_tokens=4, temperature=0.0,
        )
    )
    # First step: h1's begin() reserves its worst-case chain — all 4
    # usable blocks — so h2 parks in the same step and the end-of-step
    # gauge sample sees free==0 with the park already in the ring.
    for _ in range(300):
        serving._step()
        if h1._entry.done.is_set() and h2._entry.done.is_set():
            break
    serving._step()  # one more gauge sample so the alert clears
    assert h1.result(timeout=5).finish_reason == "length"
    assert h2.result(timeout=5).finish_reason == "length"

    dumps = [r for r in records if r.get("kind") == "blackbox"]
    assert dumps, "block exhaustion fired no blackbox dump"
    dump = dumps[0]
    assert validate_record(dump) == []
    assert dump["component"] == "serve"
    assert dump["trigger"] == "alert:block_exhaustion"
    # The ring inside the dump holds the parked admission (and the alert
    # edge itself as one of its newest entries).
    ring_events = {e["event"] for e in dump["events"]}
    assert "park" in ring_events and "alert" in ring_events
    parked = [e for e in dump["events"] if e["event"] == "park"]
    assert parked[0]["request_id"] == h2.request_id
    # Host-side context rides the dump: kvpool gauges + backlog + alerts.
    assert dump["kvpool"]["admit_backlog"] >= 1
    assert dump["kvpool"]["kv_blocks_free"] == 0
    assert any(a["rule"] == "block_exhaustion" for a in dump["alerts"])

    # The kind="alert" transitions reached the stream and the engine's
    # bounded history (fired, then cleared once retirement freed blocks).
    states = [
        (r["rule"], r["state"]) for r in records if r.get("kind") == "alert"
    ]
    assert ("block_exhaustion", "firing") in states
    assert ("block_exhaustion", "cleared") in states
    history = serving._alerts.history(2)
    assert (history[-1]["rule"], history[-1]["state"]) == (
        "block_exhaustion",
        "cleared",
    )

    # The live surfaces agree: statusz counters + the debug page retain
    # the dump after the incident cleared.
    assert serving.statusz()["flightrecorder"]["dumps"] >= 1
    debug = serving.flightrecorder.debug_page()
    assert any(
        d["trigger"] == "alert:block_exhaustion" for d in debug["dumps"]
    )
    assert {"admit", "finish"} <= {e["event"] for e in debug["events"]}
    serving._running = False
    serving.close()


# ------------------------------------------------- e2e: the incident bundle


def _stub_recorder_server(page: dict):
    """A jax-free in-process 'replica': serves a canned flight-recorder
    page — deterministic time_unix stamps for the ordering pin."""
    from http.server import BaseHTTPRequestHandler, HTTPServer

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):
            pass

        def do_GET(self):
            body = json.dumps(page).encode("utf-8")
            code = 200 if self.path == "/debug/flightrecorder" else 404
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    return HTTPServer(("127.0.0.1", 0), Handler)


@pytest.mark.serving
def test_incident_sweep_orders_cross_replica_timeline_by_wall_clock(
    setup, tmp_path
):
    """ACCEPTANCE: `bpe-tpu incident` against two in-process replicas (a
    live ServingEngine and a canned-ring peer) + one dead host: concurrent
    sweep (the dead host costs at most one timeout), every retained dump
    re-stamped with its source host, a synthesized trigger="sweep" dump
    per live ring, and ONE kind="incident" record whose merged timeline is
    ordered by absolute time_unix across hosts — the canned peer's
    early/late events deterministically sandwich every live event."""
    params, prompts = setup
    now = time.time()
    peer_page = {
        "component": "route",
        "capacity": 256,
        "recorded": 2,
        "dropped": 0,
        "events": [
            {"event": "pick", "t": 0.1, "time_unix": round(now - 1e4, 6),
             "request_id": "req-early"},
            {"event": "hop", "t": 9.0, "time_unix": round(now + 1e4, 6),
             "request_id": "req-late"},
        ],
        "dumps": [
            {"kind": "blackbox", "t": 5.0,
             "time_unix": round(now - 5e3, 6), "component": "route",
             "trigger": "manual", "events": []},
        ],
    }
    serving = ServingEngine(params, CFG, slots=1, min_bucket=8)
    out = tmp_path / "incident.jsonl"
    with serving:
        serving.generate(prompts[0], max_new_tokens=3, temperature=0.0)
        server = make_http_server(serving, port=0)
        peer = _stub_recorder_server(peer_page)
        for srv in (server, peer):
            threading.Thread(target=srv.serve_forever, daemon=True).start()
        live_url = f"127.0.0.1:{server.server_address[1]}"
        peer_url = f"127.0.0.1:{peer.server_address[1]}"
        dead_url = "127.0.0.1:1"  # nothing listens on port 1
        try:
            # POST /debug/dump: the manual-trigger endpoint answers with
            # the dump it forced, and the recorder retains it.
            req = urllib.request.Request(
                f"http://{live_url}/debug/dump", data=b"", method="POST"
            )
            with urllib.request.urlopen(req, timeout=30) as resp:
                forced = json.loads(resp.read())
            assert forced["kind"] == "blackbox"
            assert forced["trigger"] == "manual"
            t0 = time.monotonic()
            rc = incident_main(
                ["--replica", live_url, "--replica", peer_url,
                 "--replica", dead_url, "--timeout", "1.5",
                 "--out", str(out)]
            )
            # Concurrent sweep: 3 hosts, one dead — well under 2 timeouts.
            assert time.monotonic() - t0 < 3.0
        finally:
            server.shutdown()
            peer.shutdown()
    assert rc == 0  # at least one host answered

    bundle = load_records(out)
    assert bundle[0]["kind"] == "manifest"
    assert bundle[0]["run_kind"] == "incident"
    incident = bundle[-1]
    assert incident["kind"] == "incident"
    assert validate_record(incident) == []

    # Host table: live + peer online, the dead host one error row.
    rows = {row["url"]: row for row in incident["hosts"]}
    assert rows[f"http://{live_url}"]["online"] is True
    assert rows[f"http://{peer_url}"]["online"] is True
    assert rows[f"http://{dead_url}"]["online"] is False
    assert rows[f"http://{dead_url}"]["error"]

    # Every retained dump re-emitted with its source host, plus one
    # synthesized trigger="sweep" dump per live ring.
    dumps = [r for r in bundle if r.get("kind") == "blackbox"]
    assert all(validate_record(d) == [] for d in dumps)
    by_host_trigger = {(d["host"], d["trigger"]) for d in dumps}
    assert (f"http://{live_url}", "manual") in by_host_trigger
    assert (f"http://{live_url}", "sweep") in by_host_trigger
    assert (f"http://{peer_url}", "manual") in by_host_trigger
    assert (f"http://{peer_url}", "sweep") in by_host_trigger

    # THE ordering pin: the merged timeline is sorted by absolute
    # time_unix, so the canned peer's -10000s/+10000s events bracket every
    # event the live replica recorded — cross-replica wall-clock order,
    # not per-host concatenation.
    timeline = incident["timeline"]
    stamps = [e["time_unix"] for e in timeline]
    assert stamps == sorted(stamps)
    assert timeline[0]["request_id"] == "req-early"
    assert timeline[0]["host"] == f"http://{peer_url}"
    assert timeline[-1]["request_id"] == "req-late"
    live_entries = [e for e in timeline if e["host"] == f"http://{live_url}"]
    assert {"admit", "finish"} <= {e["event"] for e in live_entries}
    assert all(e["component"] == "serve" for e in live_entries)

    # The bundle is a report-readable stream: the == incident == section
    # renders and the dead host surfaces as an anomaly.
    assert "== incident (" in render_report(bundle)
    summary = summarize(bundle)
    assert summary["incident"]["hosts_online"] == 2
    assert summary["incident"]["hosts_offline"] == [f"http://{dead_url}"]
    assert any("unreachable" in a for a in summary["anomalies"])


@pytest.mark.slow  # two live replicas + HTTP sweep: full matrix only
@pytest.mark.serving
def test_incident_sweep_two_live_replicas(setup, tmp_path):
    """Heavy variant: two REAL ServingEngine replicas behind HTTP, both
    forced to dump, swept into one bundle — both hosts online, both
    replicas' dumps present, timeline stamps non-decreasing."""
    params, prompts = setup
    out = tmp_path / "incident.jsonl"
    a = ServingEngine(params, CFG, slots=1, min_bucket=8)
    b = ServingEngine(params, CFG, slots=1, min_bucket=8)
    with a, b:
        a.generate(prompts[0], max_new_tokens=3, temperature=0.0)
        b.generate(prompts[1], max_new_tokens=3, temperature=0.0)
        a.blackbox_dump("manual", force=True)
        b.blackbox_dump("manual", force=True)
        servers = [make_http_server(e, port=0) for e in (a, b)]
        for srv in servers:
            threading.Thread(target=srv.serve_forever, daemon=True).start()
        urls = [f"127.0.0.1:{s.server_address[1]}" for s in servers]
        try:
            rc = incident_main(
                ["--replica", urls[0], "--replica", urls[1],
                 "--timeout", "10", "--out", str(out)]
            )
        finally:
            for srv in servers:
                srv.shutdown()
    assert rc == 0

    bundle = load_records(out)
    incident = bundle[-1]
    assert incident["kind"] == "incident"
    assert {row["url"] for row in incident["hosts"]} == {
        f"http://{u}" for u in urls
    }
    assert all(row["online"] for row in incident["hosts"])
    dump_hosts = {r["host"] for r in bundle if r.get("kind") == "blackbox"}
    assert dump_hosts == {f"http://{u}" for u in urls}
    stamps = [e["time_unix"] for e in incident["timeline"]]
    assert stamps == sorted(stamps)
    hosts_in_timeline = {e["host"] for e in incident["timeline"]}
    assert hosts_in_timeline == {f"http://{u}" for u in urls}


# -------------------------------------------- report: fixture + compare gate


def test_report_renders_incident_section_from_committed_fixture():
    """The committed forensics fixture (tests/fixtures/blackbox_tiny.jsonl,
    also the schema checker's coverage anchor for kind=blackbox/incident)
    summarizes into the == incident == section and feeds the
    blackbox_dumps_total compare-gate row."""
    fixture = REPO / "tests" / "fixtures" / "blackbox_tiny.jsonl"
    records = load_records(fixture)
    for record in records:
        assert validate_record(record) == []

    summary = summarize(records)
    inc = summary["incident"]
    assert inc["dumps"] == 2
    assert inc["by_component"] == {"serve": 1, "route": 1}
    assert inc["by_trigger"] == {"alert:block_exhaustion": 1, "sweep": 1}
    assert inc["sweeps"] == 1 and inc["hosts"] == 2
    assert inc["timeline_entries"] == 3
    # Alert/terminal triggers surface as anomalies (sweeps do not), and
    # the unreachable host from the sweep's host table is called out.
    assert any("alert:block_exhaustion" in a for a in summary["anomalies"])
    assert any("unreachable" in a for a in summary["anomalies"])

    text = render_report(records)
    assert "== incident (2 blackbox dump(s), 1 sweep(s)) ==" in text
    assert "serve:1" in text and "alert:block_exhaustion:1" in text

    gates = extract_compare_metrics(summary)
    assert gates["blackbox_dumps_total"] == (2.0, "higher")
    # Streams without forensics records skip the row (never a false gate).
    assert "blackbox_dumps_total" not in extract_compare_metrics(
        summarize([{"step": 1, "loss": 2.0}])
    )


# ----------------------------------------------- the fetch-count acceptance


@pytest.mark.serving
def test_recording_adds_zero_device_fetches_on_tick_and_train_step(
    setup, monkeypatch, tmp_path
):
    """ACCEPTANCE (the PR 4/6 fetch-count pattern): with the flight
    recorder recording normally vs record() no-op'd, the number of
    jax.device_get / jax.block_until_ready calls is IDENTICAL on both the
    serving tick path and the training step path — recording is host-side
    bookkeeping, never a device sync — and the normal runs actually
    recorded events into their rings."""
    from bpe_transformer_tpu.training import LoopConfig, TrainHParams, train

    params, prompts = setup
    counts = {"device_get": 0, "block_until_ready": 0}
    real_get, real_block = jax.device_get, jax.block_until_ready

    def counting_get(x):
        counts["device_get"] += 1
        return real_get(x)

    def counting_block(x):
        counts["block_until_ready"] += 1
        return real_block(x)

    def serve_once():
        serving = ServingEngine(params, CFG, slots=1, min_bucket=8)
        serving._running = True
        h = serving.submit(
            Request(
                prompt_ids=tuple(prompts[0]), max_new_tokens=4,
                temperature=0.0,
            )
        )
        for _ in range(50):
            serving._step()
            if h._entry.done.is_set():
                break
        assert h.result(timeout=5).finish_reason == "length"
        recorded = serving.flightrecorder.recorded
        serving._running = False
        serving.close()
        return recorded

    text = b"the quick brown fox. " * 2000
    data = np.frombuffer(text, dtype=np.uint8).astype(np.uint16)

    def train_once(tag):
        loop = LoopConfig(
            steps=4, batch_size=8, log_every=2, eval_every=100,
            checkpoint_every=100,
            metrics_jsonl=str(tmp_path / f"t_{tag}.jsonl"),
        )
        train(
            TINY_TRAIN, TrainHParams(**TRAIN_HP), loop, data,
            log_fn=lambda *_: None,
        )

    def measure(fn):
        counts["device_get"] = counts["block_until_ready"] = 0
        monkeypatch.setattr(jax, "device_get", counting_get)
        monkeypatch.setattr(jax, "block_until_ready", counting_block)
        try:
            result = fn()
        finally:
            monkeypatch.setattr(jax, "device_get", real_get)
            monkeypatch.setattr(jax, "block_until_ready", real_block)
        return result, dict(counts)

    # Warm every jit cache once so compile-time fetches can't skew the
    # counted runs (run-order independence).
    serve_once()
    train_once("warm")

    train_recorded = {"n": 0}
    real_record = FlightRecorder.record

    def observing_record(self, event, coalesce=False, **fields):
        if self.component == "train":
            train_recorded["n"] += 1
        return real_record(self, event, coalesce=coalesce, **fields)

    # Recording ON (normal wiring, instrumented only to observe the
    # training loop's internal ring).
    monkeypatch.setattr(FlightRecorder, "record", observing_record)
    serve_recorded, counts_serve_on = measure(serve_once)
    _, counts_train_on = measure(lambda: train_once("on"))
    assert serve_recorded > 0, "serving tick recorded nothing"
    assert train_recorded["n"] > 0, "training step recorded nothing"

    # Recording OFF: record() is a pure no-op.
    monkeypatch.setattr(
        FlightRecorder, "record", lambda self, event, **fields: None
    )
    _, counts_serve_off = measure(serve_once)
    _, counts_train_off = measure(lambda: train_once("off"))
    monkeypatch.setattr(FlightRecorder, "record", real_record)

    assert counts_serve_on == counts_serve_off  # zero extra serving syncs
    assert counts_train_on == counts_train_off  # zero extra training syncs
