"""Multi-chip execution on the virtual 8-device CPU mesh.

The TPU-native analogue of testing a distributed backend without a cluster
(SURVEY §4, TPU-build additions): data-parallel psum steps and FSDP/TP
GSPMD steps must compile, run, and agree numerically with the single-device
step.

Tier-1 keeps the cheap surface (mesh/spec/validation checks, sharded
forward, the Ulysses attention-parity smoke); the full train-step parity
matrix (dp/sp/pp/ulysses x grad-accum/inner-steps) runs real 8-device
training per case — 10-80 s each on the CPU mesh — and lives behind the
``slow`` marker to keep the suite inside its wall-clock budget.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from bpe_transformer_tpu.models import TS_TEST_CONFIG, forward, init_params
from bpe_transformer_tpu.optim import adamw_init
from bpe_transformer_tpu.parallel import (
    make_dp_train_step,
    make_gspmd_train_step,
    make_mesh,
    param_specs,
    shard_batch,
    shard_params,
)
from bpe_transformer_tpu.training.train_step import (
    TrainHParams,
    make_train_step,
)

CFG = dataclasses.replace(TS_TEST_CONFIG, vocab_size=512)
HP = TrainHParams(warmup_iters=2, cosine_cycle_iters=10)


def _setup(seed=0):
    params = init_params(jax.random.PRNGKey(seed), CFG)
    opt_state = adamw_init(params)
    rng = np.random.default_rng(seed)
    x = rng.integers(0, CFG.vocab_size, size=(16, CFG.context_length))
    y = rng.integers(0, CFG.vocab_size, size=(16, CFG.context_length))
    return params, opt_state, jnp.asarray(x), jnp.asarray(y)


def test_shard_map_shim_exposes_modern_api():
    """compat.shardmap: `jax.shard_map` resolves on jax 0.4.x (aliased from
    jax.experimental) and accepts the modern check_vma= keyword this repo
    uses; jax.lax.axis_size exists alongside it.  Idempotent."""
    from bpe_transformer_tpu.compat.shardmap import ensure_shard_map

    fn = ensure_shard_map()
    assert fn is ensure_shard_map()  # second call returns the same object
    assert jax.shard_map is fn
    assert callable(jax.lax.axis_size)
    mesh = make_mesh({"data": 8})
    mapped = jax.shard_map(
        lambda x: jax.lax.psum(x, "data") + jax.lax.axis_size("data"),
        mesh=mesh,
        in_specs=PartitionSpec("data"),
        out_specs=PartitionSpec("data"),
        check_vma=False,
    )
    out = np.asarray(mapped(jnp.ones(8, jnp.int32)))
    np.testing.assert_array_equal(out, np.full(8, 16))  # psum 8 + size 8


def test_eight_virtual_devices_present():
    assert len(jax.devices()) == 8


@pytest.mark.slow
def test_dp_step_matches_single_device():
    params, opt_state, x, y = _setup()
    single = make_train_step(CFG, HP)
    p1, s1, m1 = single(params, opt_state, x, y)

    mesh = make_mesh({"data": 8})
    params2, opt_state2, x2, y2 = _setup()
    dp_step = make_dp_train_step(CFG, HP, mesh)
    x2, y2 = shard_batch((x2, y2), mesh)
    p2, s2, m2 = dp_step(params2, opt_state2, x2, y2)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        p1,
        p2,
    )


@pytest.mark.parametrize("strategy,axes", [
    ("dp", {"data": 8}),
    ("fsdp", {"data": 8}),
    ("fsdp_tp", {"data": 4, "model": 2}),
    ("tp", {"data": 2, "model": 4}),
])
@pytest.mark.slow
def test_gspmd_step_matches_single_device(strategy, axes):
    params, opt_state, x, y = _setup()
    single = make_train_step(CFG, HP)
    p1, s1, m1 = single(params, opt_state, x, y)

    mesh = make_mesh(axes)
    params2, opt_state2, x2, y2 = _setup()
    params2 = shard_params(params2, mesh, strategy)
    opt_state2 = adamw_init(params2)
    step = make_gspmd_train_step(CFG, HP, mesh, strategy, example_params=params2)
    x2, y2 = shard_batch((x2, y2), mesh)
    p2, s2, m2 = step(params2, opt_state2, x2, y2)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    # spot-check a couple of weight tensors after gathering
    np.testing.assert_allclose(
        np.asarray(p1["lm_head"]), np.asarray(jax.device_get(p2["lm_head"])),
        atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(p1["layers"][0]["ffn"]["w1"]),
        np.asarray(jax.device_get(p2["layers"][0]["ffn"]["w1"])),
        atol=1e-5,
    )


def test_fsdp_actually_shards_parameters():
    mesh = make_mesh({"data": 8})
    params = init_params(jax.random.PRNGKey(0), CFG)
    sharded = shard_params(params, mesh, "fsdp")
    emb = sharded["token_embeddings"]
    # Each device must hold 1/8th of the embedding rows.
    shard_shapes = {s.data.shape for s in emb.addressable_shards}
    assert shard_shapes == {(CFG.vocab_size // 8, CFG.d_model)}
    # Tiny norm vectors stay replicated.
    ln = sharded["ln_final"]
    assert {s.data.shape for s in ln.addressable_shards} == {(CFG.d_model,)}


def test_tp_specs_split_heads_and_ffn():
    mesh = make_mesh({"data": 2, "model": 4})
    params = init_params(jax.random.PRNGKey(0), CFG)
    specs = param_specs(params, mesh, "tp")
    attn = specs["layers"][0]["attn"]
    assert attn["q_proj"] == PartitionSpec("model", None)
    assert attn["output_proj"] == PartitionSpec(None, "model")
    ffn = specs["layers"][0]["ffn"]
    assert ffn["w1"] == PartitionSpec("model", None)
    assert ffn["w2"] == PartitionSpec(None, "model")


def test_dp_forward_inference_sharded():
    """Plain forward under a sharded batch: XLA partitions it with no code
    changes (activation sharding follows the batch)."""
    mesh = make_mesh({"data": 8})
    params = init_params(jax.random.PRNGKey(0), CFG)
    x = jnp.zeros((16, 8), dtype=jnp.int32)
    xs = shard_batch(x, mesh)
    logits = jax.jit(lambda p, t: forward(p, t, CFG))(params, xs)
    assert logits.shape == (16, 8, CFG.vocab_size)


@pytest.mark.slow
def test_sp_step_matches_single_device():
    """Context-parallel (ring attention) training step == single-device step."""
    from bpe_transformer_tpu.parallel import make_sp_train_step, shard_sp_batch

    params, opt_state, x, y = _setup()
    single = make_train_step(CFG, HP)
    p1, s1, m1 = single(params, opt_state, x, y)

    mesh = make_mesh({"data": 2, "seq": 4})
    params2, opt_state2, x2, y2 = _setup()
    step = make_sp_train_step(CFG, HP, mesh)
    x2, y2 = shard_sp_batch((x2, y2), mesh)
    p2, s2, m2 = step(params2, opt_state2, x2, y2)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        p1,
        p2,
    )


@pytest.mark.parametrize("zigzag", [False, True], ids=["ring", "zigzag"])
@pytest.mark.slow
def test_sp_grad_accum_matches_full_batch_step(zigzag):
    """Gradient accumulation INSIDE the sp (ring attention) program: each
    chip scans its local microbatch shards, one pmean over (data, seq) per
    update, and the result equals the single-device full-batch update —
    the long-context HBM-relief combo (VERDICT r3 #9)."""
    from bpe_transformer_tpu.parallel import make_sp_train_step, shard_sp_batch

    accum = 2
    params, opt_state, x, y = _setup()
    single = make_train_step(CFG, HP)
    p1, s1, m1 = single(params, opt_state, x, y)

    mesh = make_mesh({"data": 2, "seq": 4})
    params2, opt_state2, x2, y2 = _setup()
    micro = x2.shape[0] // accum
    x2 = x2.reshape(accum, micro, -1)
    y2 = y2.reshape(accum, micro, -1)
    step = make_sp_train_step(CFG, HP, mesh, zigzag=zigzag, accum_steps=accum)
    x2, y2 = shard_sp_batch((x2, y2), mesh, zigzag=zigzag, stacked=True)
    p2, s2, m2 = step(params2, opt_state2, x2, y2)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        p1,
        p2,
    )


@pytest.mark.slow
def test_sp_inner_steps_match_sequential_sp_steps():
    """inner_steps under the sp mesh: one scanned dispatch of 3 full updates
    (each with its own pmean) equals 3 sequential sp steps."""
    from bpe_transformer_tpu.parallel import make_sp_train_step, shard_sp_batch

    mesh = make_mesh({"data": 2, "seq": 4})
    params, opt_state, x, y = _setup()
    seq_step = make_sp_train_step(CFG, HP, mesh)
    xp, yp = shard_sp_batch((x, y), mesh)
    p1, s1 = params, opt_state
    for _ in range(3):
        p1, s1, m1 = seq_step(p1, s1, xp, yp)

    params2, opt_state2, x2, y2 = _setup()
    scan_step = make_sp_train_step(CFG, HP, mesh, inner_steps=3)
    xs = jnp.broadcast_to(x2, (3, *x2.shape))
    ys = jnp.broadcast_to(y2, (3, *y2.shape))
    xs, ys = shard_sp_batch((xs, ys), mesh, stacked=True)
    p2, s2, m2 = scan_step(params2, opt_state2, xs, ys)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        p1,
        p2,
    )


@pytest.mark.slow
def test_sp_forward_matches_full_forward():
    from bpe_transformer_tpu.parallel import sp_forward
    from functools import partial
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh({"seq": 8})
    params = init_params(jax.random.PRNGKey(0), CFG)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, CFG.vocab_size, size=(2, CFG.context_length))
    )
    full = forward(params, ids, CFG)

    mapped = jax.shard_map(
        partial(sp_forward, config=CFG, seq_axis="seq"),
        mesh=mesh,
        in_specs=(P(), P(None, "seq")),
        out_specs=P(None, "seq"),
        check_vma=False,
    )
    sharded = mapped(params, ids)
    np.testing.assert_allclose(
        np.asarray(full), np.asarray(sharded), atol=2e-4, rtol=1e-3
    )


# ------------------------------------------------------------ pipeline (pp)


@pytest.mark.slow
def test_pp_step_matches_single_device():
    """GPipe pipeline (4 stages) + dp must reproduce the single-device update."""
    from bpe_transformer_tpu.parallel.pp import (
        init_pp_opt_state,
        make_pp_train_step,
        shard_pp_params,
        stack_pipeline_params,
        unstack_pipeline_params,
    )

    cfg = dataclasses.replace(CFG, num_layers=4)
    params = init_params(jax.random.PRNGKey(0), cfg)
    opt_state = adamw_init(params)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(16, cfg.context_length)))
    y = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(16, cfg.context_length)))

    single = make_train_step(cfg, HP)
    p1, s1, m1 = single(params, opt_state, x, y)

    mesh = make_mesh({"data": 2, "pp": 4})
    params2 = init_params(jax.random.PRNGKey(0), cfg)
    pp_params = shard_pp_params(stack_pipeline_params(params2, 4), mesh)
    pp_opt = init_pp_opt_state(pp_params, mesh)
    step = make_pp_train_step(cfg, HP, mesh, num_microbatches=4)
    x2, y2 = shard_batch((x, y), mesh)
    p2, s2, m2 = step(pp_params, pp_opt, x2, y2)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    np.testing.assert_allclose(
        float(m1["grad_norm"]), float(m2["grad_norm"]), rtol=1e-4
    )
    restored = unstack_pipeline_params(jax.device_get(p2))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5
        ),
        p1,
        restored,
    )


@pytest.mark.slow
def test_pp_grad_accum_matches_full_batch_step():
    """Gradient accumulation AROUND the pipeline: each accumulation slice
    runs the full GPipe schedule, gradients sum in f32 through the shared
    accumulate_grads, and one update equals the single-device full-batch
    step (closes the last pp NotImplementedError; VERDICT r4 minor)."""
    from bpe_transformer_tpu.parallel.pp import (
        init_pp_opt_state,
        make_pp_train_step,
        shard_pp_params,
        stack_pipeline_params,
        unstack_pipeline_params,
    )

    accum = 2
    cfg = dataclasses.replace(CFG, num_layers=4)
    params = init_params(jax.random.PRNGKey(0), cfg)
    opt_state = adamw_init(params)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(16, cfg.context_length)))
    y = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(16, cfg.context_length)))

    single = make_train_step(cfg, HP)
    p1, s1, m1 = single(params, opt_state, x, y)

    mesh = make_mesh({"data": 2, "pp": 4})
    params2 = init_params(jax.random.PRNGKey(0), cfg)
    pp_params = shard_pp_params(stack_pipeline_params(params2, 4), mesh)
    pp_opt = init_pp_opt_state(pp_params, mesh)
    step = make_pp_train_step(
        cfg, HP, mesh, num_microbatches=2, accum_steps=accum
    )
    micro = x.shape[0] // accum
    xs = x.reshape(accum, micro, -1)
    ys = y.reshape(accum, micro, -1)
    xs, ys = shard_batch((xs, ys), mesh, stacked=True)
    p2, s2, m2 = step(pp_params, pp_opt, xs, ys)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    np.testing.assert_allclose(
        float(m1["grad_norm"]), float(m2["grad_norm"]), rtol=1e-4
    )
    restored = unstack_pipeline_params(jax.device_get(p2))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5
        ),
        p1,
        restored,
    )


@pytest.mark.slow
def test_pp_inner_steps_match_sequential_pp_steps():
    """inner_steps under pp: one scanned dispatch of 3 full pipelined
    updates equals 3 sequential pp steps."""
    from bpe_transformer_tpu.parallel.pp import (
        init_pp_opt_state,
        make_pp_train_step,
        shard_pp_params,
        stack_pipeline_params,
    )

    cfg = dataclasses.replace(CFG, num_layers=4)
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(8, cfg.context_length)))
    y = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(8, cfg.context_length)))
    mesh = make_mesh({"data": 2, "pp": 4})

    def fresh():
        params = init_params(jax.random.PRNGKey(2), cfg)
        pp_params = shard_pp_params(stack_pipeline_params(params, 4), mesh)
        return pp_params, init_pp_opt_state(pp_params, mesh)

    seq_step = make_pp_train_step(cfg, HP, mesh, num_microbatches=2)
    p1, s1 = fresh()
    xp, yp = shard_batch((x, y), mesh)
    for _ in range(3):
        p1, s1, m1 = seq_step(p1, s1, xp, yp)

    scan_step = make_pp_train_step(
        cfg, HP, mesh, num_microbatches=2, inner_steps=3
    )
    p2, s2 = fresh()
    xs = jnp.broadcast_to(x, (3, *x.shape))
    ys = jnp.broadcast_to(y, (3, *y.shape))
    xs, ys = shard_batch((xs, ys), mesh, stacked=True)
    p2, s2, m2 = scan_step(p2, s2, xs, ys)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        jax.device_get(p1),
        jax.device_get(p2),
    )


def test_pp_accum_and_inner_both_raise():
    from bpe_transformer_tpu.parallel.pp import make_pp_train_step

    mesh = make_mesh({"data": 2, "pp": 4})
    with pytest.raises(ValueError, match="cannot both exceed 1"):
        make_pp_train_step(CFG, HP, mesh, accum_steps=2, inner_steps=2)


def test_pp_stack_unstack_roundtrip():
    from bpe_transformer_tpu.parallel.pp import (
        stack_pipeline_params,
        unstack_pipeline_params,
    )

    cfg = dataclasses.replace(CFG, num_layers=4)
    params = init_params(jax.random.PRNGKey(1), cfg)
    restored = unstack_pipeline_params(stack_pipeline_params(params, 2))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        params,
        restored,
    )


def test_hybrid_mesh_degenerate_and_validation():
    from bpe_transformer_tpu.parallel import make_hybrid_mesh

    # dcn all-1 degenerates to a plain ICI mesh.
    mesh = make_hybrid_mesh({"data": 4, "model": 2})
    assert dict(mesh.shape) == {"data": 4, "model": 2}

    with pytest.raises(ValueError, match="not present"):
        make_hybrid_mesh({"data": 8}, {"model": 2})
    with pytest.raises(ValueError, match="needs"):
        make_hybrid_mesh({"data": 8}, {"data": 2})


# ------------------------------------------------- zig-zag ring attention


@pytest.mark.slow
def test_zigzag_ring_attention_matches_xla_and_ring():
    """Balanced zig-zag schedule == materialized causal attention == the
    contiguous ring, after the layout permutation round-trip."""
    from functools import partial

    from bpe_transformer_tpu.ops.core import causal_mask, scaled_dot_product_attention
    from bpe_transformer_tpu.parallel.ring_attention import (
        ring_self_attention,
        zigzag_indices,
        zigzag_inverse_indices,
        zigzag_ring_self_attention,
    )

    n = 8
    B, H, S, D = 2, 2, 64, 16
    mesh = make_mesh({"seq": n})
    rng = np.random.default_rng(0)
    q, k, v = (
        jnp.asarray(rng.standard_normal((B, H, S, D)).astype(np.float32))
        for _ in range(3)
    )
    expected = scaled_dot_product_attention(q, k, v, causal_mask(S))

    spec = PartitionSpec(None, None, "seq", None)
    ring = jax.shard_map(
        partial(ring_self_attention, axis_name="seq", causal=True),
        mesh=mesh, in_specs=(spec,) * 3, out_specs=spec, check_vma=False,
    )
    np.testing.assert_allclose(np.asarray(ring(q, k, v)), np.asarray(expected), atol=1e-5)

    perm = zigzag_indices(S, n)
    inv = zigzag_inverse_indices(S, n)
    zig = jax.shard_map(
        partial(zigzag_ring_self_attention, axis_name="seq"),
        mesh=mesh, in_specs=(spec,) * 3, out_specs=spec, check_vma=False,
    )
    out_zig = zig(q[..., perm, :], k[..., perm, :], v[..., perm, :])[..., inv, :]
    np.testing.assert_allclose(np.asarray(out_zig), np.asarray(expected), atol=1e-5)


@pytest.mark.slow
def test_ring_attention_bf16_inputs_match_f32_reference():
    """The compute-dtype matmul rule (bf16 inputs, f32 accumulation) must
    track the f32 oracle within bf16 tolerance for BOTH XLA ring schedules.
    All other ring tests run f32, where preferred_element_type is a no-op —
    this is the only coverage of the precision-affecting path."""
    from functools import partial

    from bpe_transformer_tpu.ops.core import causal_mask, scaled_dot_product_attention
    from bpe_transformer_tpu.parallel.ring_attention import (
        ring_self_attention,
        zigzag_indices,
        zigzag_inverse_indices,
        zigzag_ring_self_attention,
    )

    n = 8
    B, H, S, D = 2, 2, 64, 16
    mesh = make_mesh({"seq": n})
    rng = np.random.default_rng(1)
    q32, k32, v32 = (
        jnp.asarray(rng.standard_normal((B, H, S, D)).astype(np.float32))
        for _ in range(3)
    )
    expected = scaled_dot_product_attention(q32, k32, v32, causal_mask(S))
    q, k, v = (x.astype(jnp.bfloat16) for x in (q32, k32, v32))

    spec = PartitionSpec(None, None, "seq", None)
    ring = jax.shard_map(
        partial(ring_self_attention, axis_name="seq", causal=True),
        mesh=mesh, in_specs=(spec,) * 3, out_specs=spec, check_vma=False,
    )
    out = ring(q, k, v)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(expected), atol=0.03
    )

    perm = zigzag_indices(S, n)
    inv = zigzag_inverse_indices(S, n)
    zig = jax.shard_map(
        partial(zigzag_ring_self_attention, axis_name="seq"),
        mesh=mesh, in_specs=(spec,) * 3, out_specs=spec, check_vma=False,
    )
    out_zig = zig(q[..., perm, :], k[..., perm, :], v[..., perm, :])[..., inv, :]
    np.testing.assert_allclose(
        np.asarray(out_zig, np.float32), np.asarray(expected), atol=0.03
    )


def test_zigzag_positions_cover_sequence():
    from bpe_transformer_tpu.parallel.ring_attention import (
        zigzag_indices,
        zigzag_positions,
    )

    n, S = 4, 64
    all_pos = jnp.concatenate(
        [zigzag_positions(i, S // n, n) for i in range(n)]
    )
    assert sorted(np.asarray(all_pos).tolist()) == list(range(S))
    # positions agree with the layout permutation
    np.testing.assert_array_equal(np.asarray(all_pos), np.asarray(zigzag_indices(S, n)))


@pytest.mark.slow
def test_sp_zigzag_step_matches_single_device():
    """Zig-zag context-parallel step == single-device step: the permutation
    is transparent to the loss (targets ride the same layout)."""
    from bpe_transformer_tpu.parallel import make_sp_train_step, shard_sp_batch

    params, opt_state, x, y = _setup()
    single = make_train_step(CFG, HP)
    p1, s1, m1 = single(params, opt_state, x, y)

    mesh = make_mesh({"data": 2, "seq": 4})
    params2, opt_state2, x2, y2 = _setup()
    step = make_sp_train_step(CFG, HP, mesh, zigzag=True)
    x2, y2 = shard_sp_batch((x2, y2), mesh, zigzag=True)
    p2, s2, m2 = step(params2, opt_state2, x2, y2)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        p1,
        p2,
    )




def test_sp_flash_with_ring_kv_chunk_raises():
    """attention_impl="flash" ignores ring_kv_chunk inside the ring (the
    Pallas kernel tiles by flash_block_size); the combination must fail
    loudly instead of silently dropping the knob."""
    from bpe_transformer_tpu.parallel import make_sp_train_step

    mesh = make_mesh({"data": 2, "seq": 4})
    cfg = dataclasses.replace(CFG, attention_impl="flash", ring_kv_chunk=4)
    with pytest.raises(ValueError, match="ring_kv_chunk"):
        make_sp_train_step(cfg, HP, mesh)


@pytest.mark.slow
def test_dp_grad_accum_matches_full_batch_step():
    """Gradient accumulation under the explicit-collective dp mesh: scanning
    2 microbatches per chip then one all-reduced update equals the
    single-device full-batch step (VERDICT r2 #5)."""
    params, opt_state, x, y = _setup()
    single = make_train_step(CFG, HP)
    p1, s1, m1 = single(params, opt_state, x, y)

    mesh = make_mesh({"data": 8})
    params2, opt_state2, x2, y2 = _setup()
    accum = 2
    micro = x2.shape[0] // accum  # 8, divides the data axis
    x2 = x2.reshape(accum, micro, -1)
    y2 = y2.reshape(accum, micro, -1)
    step = make_dp_train_step(CFG, HP, mesh, accum_steps=accum)
    x2, y2 = shard_batch((x2, y2), mesh, stacked=True)
    p2, s2, m2 = step(params2, opt_state2, x2, y2)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        p1,
        p2,
    )


@pytest.mark.parametrize("strategy,axes,accum", [
    ("fsdp", {"data": 8}, 2),  # micro=8 divides data=8
    ("fsdp_tp", {"data": 4, "model": 2}, 4),  # micro=4 divides data=4
])
@pytest.mark.slow
def test_gspmd_grad_accum_matches_full_batch_step(strategy, axes, accum):
    """Gradient accumulation compiled INSIDE the GSPMD program: the
    accumulation scan composes with XLA-derived FSDP collectives and equals
    the single-device full-batch update."""
    params, opt_state, x, y = _setup()
    single = make_train_step(CFG, HP)
    p1, s1, m1 = single(params, opt_state, x, y)

    mesh = make_mesh(axes)
    params2, opt_state2, x2, y2 = _setup()
    params2 = shard_params(params2, mesh, strategy)
    opt_state2 = adamw_init(params2)
    micro = x2.shape[0] // accum
    x2 = x2.reshape(accum, micro, -1)
    y2 = y2.reshape(accum, micro, -1)
    step = make_gspmd_train_step(
        CFG, HP, mesh, strategy, example_params=params2, accum_steps=accum
    )
    x2, y2 = shard_batch((x2, y2), mesh, stacked=True)
    p2, s2, m2 = step(params2, opt_state2, x2, y2)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(p1["lm_head"]), np.asarray(jax.device_get(p2["lm_head"])),
        atol=1e-5,
    )


@pytest.mark.slow
def test_dp_inner_steps_match_sequential_dp_steps():
    """inner_steps under the dp mesh: one scanned dispatch of 3 updates
    equals 3 sequential dp steps (VERDICT r2 #5)."""
    mesh = make_mesh({"data": 8})
    params, opt_state, x, y = _setup()
    seq_step = make_dp_train_step(CFG, HP, mesh)
    xp, yp = shard_batch((x, y), mesh)
    p1, s1 = params, opt_state
    for _ in range(3):
        p1, s1, m1 = seq_step(p1, s1, xp, yp)

    params2, opt_state2, x2, y2 = _setup()
    scan_step = make_dp_train_step(CFG, HP, mesh, inner_steps=3)
    xs = jnp.broadcast_to(x2, (3, *x2.shape))
    ys = jnp.broadcast_to(y2, (3, *y2.shape))
    xs, ys = shard_batch((xs, ys), mesh, stacked=True)
    p2, s2, m2 = scan_step(params2, opt_state2, xs, ys)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        p1,
        p2,
    )


# ------------------------------------------------- ulysses (all-to-all sp)


def test_ulysses_attention_matches_dense():
    """The all-to-all head scatter reproduces dense causal attention: one
    all_to_all to head-sharded, full-seq attention, inverse all_to_all."""
    from functools import partial

    from bpe_transformer_tpu.ops.core import causal_mask, scaled_dot_product_attention
    from bpe_transformer_tpu.parallel.ulysses import ulysses_attention

    mesh = make_mesh({"data": 2, "seq": 4})
    rng = np.random.default_rng(0)
    B, H, S, D = 2, 8, 32, 16
    q, k, v = (
        jnp.asarray(rng.standard_normal((B, H, S, D)).astype(np.float32))
        for _ in range(3)
    )
    dense = scaled_dot_product_attention(q, k, v, causal_mask(S))

    spec = PartitionSpec("data", None, "seq")
    mapped = jax.shard_map(
        partial(ulysses_attention, axis_name="seq"),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    out = mapped(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense), atol=2e-5)


@pytest.mark.parametrize(
    "num_heads,kv_heads",
    [(4, None), (4, 2), (8, 4)],
    ids=["mha", "gqa_expanded", "gqa_compact"],
)
@pytest.mark.slow
def test_sp_ulysses_step_matches_single_device(num_heads, kv_heads):
    """A full train step under the Ulysses schedule equals the single-device
    update (gradients flow through the all_to_alls — their transpose is the
    inverse all_to_all).  gqa_expanded: kv_heads (2) does not divide the seq
    axis (4), so K/V ship expanded; gqa_compact: kv_heads (4) does, so the
    compact slice/re-expand path runs — including its BACKWARD, which relies
    on the repeat-VJP summing each group so the sliced duplicates' zero
    cotangents wash out."""
    from bpe_transformer_tpu.parallel import make_sp_train_step, shard_sp_batch

    cfg = dataclasses.replace(CFG, num_heads=num_heads, num_kv_heads=kv_heads)
    params = init_params(jax.random.PRNGKey(0), cfg)
    opt_state = adamw_init(params)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(16, cfg.context_length)))
    y = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(16, cfg.context_length)))

    single = make_train_step(cfg, HP)
    p1, s1, m1 = single(params, opt_state, x, y)

    mesh = make_mesh({"data": 2, "seq": 4})
    params2 = init_params(jax.random.PRNGKey(0), cfg)
    step = make_sp_train_step(cfg, HP, mesh, ulysses=True)
    xp, yp = shard_sp_batch((x, y), mesh)
    p2, s2, m2 = step(params2, adamw_init(params2), xp, yp)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        p1,
        jax.device_get(p2),
    )


@pytest.mark.slow
def test_sp_ulysses_forward_matches_full_forward():
    from functools import partial

    from bpe_transformer_tpu.parallel import sp_forward

    mesh = make_mesh({"data": 2, "seq": 4})
    params = init_params(jax.random.PRNGKey(1), CFG)
    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(0, CFG.vocab_size, size=(4, CFG.context_length)))
    dense = forward(params, ids, CFG)

    mapped = jax.shard_map(
        partial(sp_forward, config=CFG, seq_axis="seq", ulysses=True),
        mesh=mesh,
        in_specs=(PartitionSpec(), PartitionSpec("data", "seq")),
        out_specs=PartitionSpec("data", "seq", None),
        check_vma=False,
    )
    out = mapped(params, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense), atol=3e-5)


def test_sp_ulysses_validation():
    from bpe_transformer_tpu.parallel import make_sp_train_step

    mesh = make_mesh({"data": 2, "seq": 4})
    with pytest.raises(ValueError, match="mutually exclusive"):
        make_sp_train_step(CFG, HP, mesh, zigzag=True, ulysses=True)
    cfg3 = dataclasses.replace(CFG, num_heads=2, d_model=32)
    with pytest.raises(ValueError, match="must be a multiple"):
        make_sp_train_step(cfg3, HP, mesh, ulysses=True)


@pytest.mark.slow
def test_sp_ulysses_gqa_compact_kv_path():
    """When kv_heads also divides the seq axis the K/V all_to_alls ship the
    COMPACT kv heads (group× less communication); numerics must match the
    dense forward exactly like the expanded path."""
    from functools import partial

    from bpe_transformer_tpu.parallel import sp_forward

    cfg = dataclasses.replace(CFG, num_heads=8, d_model=64, num_kv_heads=4)
    mesh = make_mesh({"data": 2, "seq": 4})
    params = init_params(jax.random.PRNGKey(2), cfg)
    rng = np.random.default_rng(2)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(4, cfg.context_length)))
    dense = forward(params, ids, cfg)

    mapped = jax.shard_map(
        partial(sp_forward, config=cfg, seq_axis="seq", ulysses=True),
        mesh=mesh,
        in_specs=(PartitionSpec(), PartitionSpec("data", "seq")),
        out_specs=PartitionSpec("data", "seq", None),
        check_vma=False,
    )
    out = mapped(params, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense), atol=3e-5)


@pytest.mark.slow
def test_sp_ulysses_grad_accum_matches_full_batch_step():
    """Ulysses composes with gradient accumulation (the schedule-independent
    accumulate_grads scan): equals the single-device full-batch update."""
    from bpe_transformer_tpu.parallel import make_sp_train_step, shard_sp_batch

    accum = 2
    params, opt_state, x, y = _setup()
    single = make_train_step(CFG, HP)
    p1, s1, m1 = single(params, opt_state, x, y)

    mesh = make_mesh({"data": 2, "seq": 4})
    params2, opt_state2, x2, y2 = _setup()
    micro = x2.shape[0] // accum
    x2 = x2.reshape(accum, micro, -1)
    y2 = y2.reshape(accum, micro, -1)
    step = make_sp_train_step(CFG, HP, mesh, ulysses=True, accum_steps=accum)
    x2, y2 = shard_sp_batch((x2, y2), mesh, stacked=True)
    p2, s2, m2 = step(params2, opt_state2, x2, y2)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        p1,
        p2,
    )


@pytest.mark.slow
def test_sp_ulysses_flash_inner_attention_matches_xla():
    """attention_impl="flash" routes Ulysses' full-sequence inner attention
    through the Pallas kernel (interpret mode on CPU): step parity vs the
    single-device update still holds."""
    from bpe_transformer_tpu.parallel import make_sp_train_step, shard_sp_batch

    cfg = dataclasses.replace(CFG, attention_impl="flash")
    params, opt_state, x, y = _setup()
    single = make_train_step(CFG, HP)
    p1, s1, m1 = single(params, opt_state, x, y)

    mesh = make_mesh({"data": 2, "seq": 4})
    params2, opt_state2, x2, y2 = _setup()
    step = make_sp_train_step(cfg, HP, mesh, ulysses=True)
    xp, yp = shard_sp_batch((x2, y2), mesh)
    p2, s2, m2 = step(params2, opt_state2, xp, yp)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-4)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5
        ),
        p1,
        p2,
    )
