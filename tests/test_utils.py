"""Auxiliary subsystems: profiling harness, debug toggles, metrics sinks.

The reference has none of these as library code (SURVEY §5 — profiling in
notebook cells, no logging calls, unused wandb dep); these tests pin the
TPU-native versions.
"""

import json

import jax
import jax.numpy as jnp
import pytest

from bpe_transformer_tpu.utils import (
    MetricsLogger,
    StepTimer,
    check_finite,
    nan_checks,
    profile_trace,
    time_fn,
)


def test_time_fn_reports_timings():
    fn = jax.jit(lambda x: x * 2.0)
    x = jnp.ones((16, 16))
    out = time_fn(fn, x, iters=3, warmup=1)
    assert out["iters"] == 3
    assert 0 < out["best_s"] <= out["mean_s"]


@pytest.mark.slow
def test_profile_trace_writes_artifacts(tmp_path):
    logdir = tmp_path / "trace"
    with profile_trace(str(logdir)):
        jax.block_until_ready(jnp.dot(jnp.ones((32, 32)), jnp.ones((32, 32))))
    # jax.profiler writes plugins/profile/<run>/ under the logdir.
    assert any(logdir.rglob("*.xplane.pb")), "no xplane trace written"


def test_step_timer_windows():
    timer = StepTimer(n_chips=4)
    timer.update(1000)
    timer.update(1000)
    snap = timer.snapshot()
    assert snap["window_tokens"] == 2000
    assert snap["tokens_per_sec"] == pytest.approx(
        4 * snap["tokens_per_sec_per_chip"]
    )
    # Window resets.
    assert timer.snapshot()["window_tokens"] == 0
    assert timer.total_tokens == 2000


def test_metrics_logger_jsonl_and_stdout(tmp_path):
    path = tmp_path / "m.jsonl"
    lines = []
    with MetricsLogger(stdout=True, jsonl_path=path, log_fn=lines.append) as m:
        m.log({"step": 1, "loss": 2.5})
        m.log({"step": 2, "loss": 2.25})
    records = [json.loads(line) for line in path.read_text().splitlines()]
    assert records == [{"step": 1, "loss": 2.5}, {"step": 2, "loss": 2.25}]
    assert lines and "loss 2.5" in lines[0]


def test_step_timer_empty_window_is_safe():
    timer = StepTimer()
    snap = timer.snapshot()  # no update() calls: zero tokens, no div-zero
    assert snap["window_tokens"] == 0
    assert snap["tokens_per_sec"] == 0.0
    assert "mfu" not in snap  # no flops_per_token given


def test_step_timer_exclude_discounts_non_step_time():
    timer = StepTimer()
    timer.update(100)
    # Excluding more than the elapsed window clamps at the epsilon floor —
    # proof the exclusion is subtracted from the window's elapsed time.
    timer.exclude(1000.0)
    assert timer.snapshot()["window_seconds"] == pytest.approx(1e-9)
    # snapshot() resets the exclusion along with the window (asserted on
    # the counter itself: a leaked exclusion would clamp window_seconds to
    # the same epsilon floor, making a time-based assertion vacuous).
    assert timer._window_excluded == 0.0


def test_step_timer_mfu_absent_on_unknown_device():
    # CPU test host: peak FLOPs unknown, so mfu is omitted (not garbage).
    timer = StepTimer(flops_per_token=1e6)
    timer.update(1000)
    assert "mfu" not in timer.snapshot()


def test_metrics_logger_noop_without_sinks():
    MetricsLogger().log({"step": 1})  # must not raise


def test_metrics_logger_wandb_absent_raises_before_opening_jsonl(
    tmp_path, monkeypatch
):
    import sys

    monkeypatch.setitem(sys.modules, "wandb", None)  # force ImportError
    path = tmp_path / "m.jsonl"
    with pytest.raises(ImportError, match="wandb"):
        MetricsLogger(jsonl_path=path, wandb_project="p")
    # The wandb check ran first: no stray half-opened JSONL file.
    assert not path.exists()


def test_metrics_logger_wandb_sink_skips_structured_records(tmp_path, monkeypatch):
    import sys
    import types

    logged = []
    stub = types.SimpleNamespace(
        init=lambda **kw: types.SimpleNamespace(
            log=lambda record, step=None: logged.append((record, step)),
            finish=lambda: None,
        )
    )
    monkeypatch.setitem(sys.modules, "wandb", stub)
    logger = MetricsLogger(jsonl_path=tmp_path / "m.jsonl", wandb_project="p")
    logger.log({"kind": "manifest", "git_sha": "abc"})  # structured: skipped
    logger.log({"step": 1, "loss": 2.0})
    logger.log({"kind": "footer", "clean": True})
    logger.close()
    # Only the flat step record reached wandb (a kind-record logged with
    # step=None would advance wandb's auto-step and drop early steps); the
    # JSONL still carries all three.
    assert logged == [({"step": 1, "loss": 2.0}, 1)]
    assert len((tmp_path / "m.jsonl").read_text().splitlines()) == 3


def test_metrics_logger_log_after_close_is_noop(tmp_path):
    path = tmp_path / "m.jsonl"
    logger = MetricsLogger(jsonl_path=path)
    logger.log({"step": 1})
    logger.close()
    logger.log({"step": 2})  # crash-path flush after close: silent no-op
    logger.close()  # close is idempotent
    records = [json.loads(line) for line in path.read_text().splitlines()]
    assert records == [{"step": 1}]


def test_nan_checks_catches_nan_at_the_producing_op():
    with nan_checks():
        with pytest.raises(FloatingPointError):
            jax.block_until_ready(jnp.log(jnp.array(-1.0)) * 0.0)
    # Restored afterwards: the same expression is fine outside the block.
    jax.block_until_ready(jnp.log(jnp.array(-1.0)) * 0.0)


def test_check_finite():
    good = {"a": jnp.ones(3), "b": {"c": jnp.zeros(2)}}
    check_finite(good)
    bad = {"a": jnp.ones(3), "b": {"c": jnp.array([1.0, float("nan")])}}
    with pytest.raises(FloatingPointError, match="b"):
        check_finite(bad, name="params")
