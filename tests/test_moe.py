"""Mixture-of-experts FFN: routing numerics, capacity, and expert parallelism.

The MoE layer has no reference precedent; these tests pin its semantics the
same way the reference pins dense ops — against a transparent per-token
reference implementation — and validate the expert-parallel (GSPMD) step on
the virtual 8-device mesh.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bpe_transformer_tpu.models import TS_TEST_CONFIG, forward, init_params
from bpe_transformer_tpu.models.moe import expert_capacity, init_moe_params, switch_ffn
from bpe_transformer_tpu.optim import adamw_init
from bpe_transformer_tpu.parallel import make_mesh, make_gspmd_train_step, shard_batch, shard_params
from bpe_transformer_tpu.training.train_step import TrainHParams, make_train_step

MOE_CFG = dataclasses.replace(
    TS_TEST_CONFIG,
    vocab_size=512,
    ffn_type="moe",
    n_experts=4,
    capacity_factor=2.0,
)


def _reference_switch(tokens, params, cap):
    """Per-token numpy reference: route to argmax expert, drop beyond cap."""
    router = np.asarray(params["router"], np.float32)
    w1, w2, w3 = (np.asarray(params[k], np.float32) for k in ("w1", "w2", "w3"))
    logits = tokens @ router.T
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    idx = probs.argmax(-1)
    out = np.zeros_like(tokens)
    counts = {e: 0 for e in range(router.shape[0])}
    for n in range(tokens.shape[0]):
        e = int(idx[n])
        if counts[e] >= cap:
            continue
        counts[e] += 1
        x = tokens[n]
        h = (x @ w1[e].T) / (1 + np.exp(-(x @ w1[e].T))) * (x @ w3[e].T)
        out[n] = probs[n, e] * (h @ w2[e].T)
    return out


def test_switch_ffn_matches_per_token_reference():
    cfg = dataclasses.replace(MOE_CFG, capacity_factor=100.0)  # no drops
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(6, 5, cfg.d_model)).astype(np.float32))

    out, aux = switch_ffn(x, params, cfg)
    tokens = np.asarray(x, np.float32).reshape(-1, cfg.d_model)
    ref = _reference_switch(tokens, params, cap=10**9)
    np.testing.assert_allclose(
        np.asarray(out).reshape(-1, cfg.d_model), ref, atol=1e-5
    )
    assert float(aux) > 0.0


@pytest.mark.parametrize("top_k", [1, 2])
def test_gather_dispatch_matches_einsum(top_k):
    """moe_dispatch="gather" is the same routing function as "einsum":
    identical assignments, positions, gates, and drops — outputs and aux
    must agree (incl. under capacity pressure) and so must gradients."""
    cfg = dataclasses.replace(
        MOE_CFG, router_top_k=top_k, capacity_factor=0.5
    )  # tight capacity: drops exercised
    cfg_g = dataclasses.replace(cfg, moe_dispatch="gather")
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(4, 8, cfg.d_model)).astype(np.float32))

    out_e, aux_e = switch_ffn(x, params, cfg)
    out_g, aux_g = switch_ffn(x, params, cfg_g)
    np.testing.assert_allclose(np.asarray(out_g), np.asarray(out_e), atol=1e-5)
    np.testing.assert_allclose(float(aux_g), float(aux_e), rtol=1e-6)

    def loss(p, c):
        o, a = switch_ffn(x, p, c)
        return jnp.sum(o**2) + a

    g_e = jax.grad(loss)(params, cfg)
    g_g = jax.grad(loss)(params, cfg_g)
    for k in g_e:
        np.testing.assert_allclose(
            np.asarray(g_g[k]), np.asarray(g_e[k]), atol=1e-4
        )


def test_switch_ffn_respects_capacity():
    cfg = dataclasses.replace(MOE_CFG, capacity_factor=0.5)
    params = init_moe_params(jax.random.PRNGKey(1), cfg)
    rng = np.random.default_rng(1)
    n_tok = 32
    x = jnp.asarray(rng.normal(size=(1, n_tok, cfg.d_model)).astype(np.float32))
    cap = expert_capacity(n_tok, cfg.n_experts, cfg.capacity_factor)

    out, _ = switch_ffn(x, params, cfg)
    ref = _reference_switch(
        np.asarray(x, np.float32).reshape(-1, cfg.d_model), params, cap
    )
    np.testing.assert_allclose(
        np.asarray(out).reshape(-1, cfg.d_model), ref, atol=1e-5
    )
    # Overflow tokens exist and produce exactly-zero output rows.
    dropped = np.all(ref == 0.0, axis=-1)
    assert dropped.any()


def _reference_topk(tokens, params, cap, k):
    """Per-token numpy reference for top-k routing: gates renormalized over
    the chosen experts, capacity filled rank-major (all first choices queue
    before any second choice)."""
    router = np.asarray(params["router"], np.float32)
    w1, w2, w3 = (np.asarray(params[kk], np.float32) for kk in ("w1", "w2", "w3"))
    logits = tokens @ router.T
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    order = np.argsort(-probs, axis=-1, kind="stable")[:, :k]
    topk_probs = np.take_along_axis(probs, order, axis=-1)
    gates = topk_probs / topk_probs.sum(-1, keepdims=True)
    out = np.zeros_like(tokens)
    counts = {e: 0 for e in range(router.shape[0])}
    for rank in range(k):
        for n in range(tokens.shape[0]):
            e = int(order[n, rank])
            if counts[e] >= cap:
                continue
            counts[e] += 1
            x = tokens[n]
            h = (x @ w1[e].T) / (1 + np.exp(-(x @ w1[e].T))) * (x @ w3[e].T)
            out[n] += gates[n, rank] * (h @ w2[e].T)
    return out


def test_top2_ffn_matches_per_token_reference():
    cfg = dataclasses.replace(MOE_CFG, router_top_k=2, capacity_factor=100.0)
    params = init_moe_params(jax.random.PRNGKey(3), cfg)
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(6, 5, cfg.d_model)).astype(np.float32))

    out, aux = switch_ffn(x, params, cfg)
    tokens = np.asarray(x, np.float32).reshape(-1, cfg.d_model)
    ref = _reference_topk(tokens, params, cap=10**9, k=2)
    np.testing.assert_allclose(
        np.asarray(out).reshape(-1, cfg.d_model), ref, atol=1e-5
    )
    assert float(aux) > 0.0


def test_top2_capacity_fills_rank_major():
    """With tight capacity, every token's first choice outranks any token's
    second choice — pinned against the rank-major numpy reference."""
    cfg = dataclasses.replace(MOE_CFG, router_top_k=2, capacity_factor=0.75)
    params = init_moe_params(jax.random.PRNGKey(4), cfg)
    rng = np.random.default_rng(4)
    n_tok = 32
    x = jnp.asarray(rng.normal(size=(1, n_tok, cfg.d_model)).astype(np.float32))
    cap = expert_capacity(n_tok, cfg.n_experts, cfg.capacity_factor)

    out, _ = switch_ffn(x, params, cfg)
    ref = _reference_topk(
        np.asarray(x, np.float32).reshape(-1, cfg.d_model), params, cap, k=2
    )
    np.testing.assert_allclose(
        np.asarray(out).reshape(-1, cfg.d_model), ref, atol=1e-5
    )


def test_uniform_router_aux_is_near_one():
    """With a zero router every expert gets probability 1/E; aux -> ~1."""
    cfg = MOE_CFG
    params = init_moe_params(jax.random.PRNGKey(2), cfg)
    params = dict(params, router=jnp.zeros_like(params["router"]))
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(4, 16, cfg.d_model)).astype(np.float32))
    _, aux = switch_ffn(x, params, cfg)
    assert float(aux) == pytest.approx(1.0, abs=1e-5)


def test_moe_lm_trains():
    """Full LM with MoE FFNs: loss (incl. aux) decreases over a few steps."""
    cfg = MOE_CFG
    params = init_params(jax.random.PRNGKey(0), cfg)
    opt_state = adamw_init(params)
    step = make_train_step(cfg, TrainHParams(warmup_iters=1, cosine_cycle_iters=50))
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, size=(8, cfg.context_length))
    x = jnp.asarray(ids)
    y = jnp.asarray(np.roll(ids, -1, axis=1))
    losses = []
    for _ in range(8):
        params, opt_state, metrics = step(params, opt_state, x, y)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]


@pytest.mark.parametrize("dispatch", ["einsum", "gather"])
@pytest.mark.slow
def test_ep_step_matches_single_device(dispatch):
    """dp_ep GSPMD step on a (data, expert) mesh reproduces the single-device
    update (routing and capacity drops are deterministic) — for BOTH dispatch
    formulations (gather must stay mesh-compilable, not just fast)."""
    cfg = dataclasses.replace(MOE_CFG, moe_dispatch=dispatch)
    hp = TrainHParams(warmup_iters=2, cosine_cycle_iters=10)
    params = init_params(jax.random.PRNGKey(0), cfg)
    opt_state = adamw_init(params)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(16, cfg.context_length)))
    y = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(16, cfg.context_length)))

    single = make_train_step(cfg, hp)
    p1, s1, m1 = single(params, opt_state, x, y)

    mesh = make_mesh({"data": 2, "expert": 4})
    params2 = init_params(jax.random.PRNGKey(0), cfg)
    params2 = shard_params(params2, mesh, "dp_ep")
    opt2 = adamw_init(params2)
    step = make_gspmd_train_step(cfg, hp, mesh, "dp_ep", example_params=params2)
    x2, y2 = shard_batch((x, y), mesh)
    p2, s2, m2 = step(params2, opt2, x2, y2)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5
        ),
        p1,
        jax.device_get(p2),
    )


@pytest.mark.slow
def test_sp_moe_step_matches_single_device():
    """Context-parallel (ring attention) step with MoE FFNs == single-device
    step.  Capacity is generous so per-shard routing has no drops.  The aux
    weight is zeroed: the load-balance loss is computed per dispatch group
    (the Switch convention), so under sp it averages shard-local products
    rather than reproducing the global product — expert compute and the task
    loss must still match the single-device step exactly."""
    from bpe_transformer_tpu.parallel import make_sp_train_step, shard_sp_batch

    cfg = dataclasses.replace(
        MOE_CFG, capacity_factor=16.0, router_aux_weight=0.0
    )
    hp = TrainHParams(warmup_iters=2, cosine_cycle_iters=10)
    params = init_params(jax.random.PRNGKey(0), cfg)
    opt_state = adamw_init(params)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(4, cfg.context_length)))
    y = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(4, cfg.context_length)))

    single = make_train_step(cfg, hp)
    p1, s1, m1 = single(params, opt_state, x, y)

    mesh = make_mesh({"data": 2, "seq": 4})
    params2 = init_params(jax.random.PRNGKey(0), cfg)
    opt2 = adamw_init(params2)
    step = make_sp_train_step(cfg, hp, mesh)
    x2, y2 = shard_sp_batch((x, y), mesh)
    p2, s2, m2 = step(params2, opt2, x2, y2)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5
        ),
        p1,
        jax.device_get(p2),
    )


@pytest.mark.slow
def test_sp_moe_loop_trains(tmp_path):
    """The training loop accepts parallel="sp" with an MoE config (the hole
    closed in round 2) and the loss decreases."""
    from bpe_transformer_tpu.training.loop import LoopConfig, train

    cfg = dataclasses.replace(MOE_CFG, capacity_factor=4.0, router_top_k=2)
    # Learnable data (uniform-random tokens sit at the entropy floor already):
    # a repeating ramp makes next-token prediction reducible within steps.
    data = np.tile(np.arange(cfg.vocab_size, dtype=np.int32), 40)
    summary = train(
        cfg,
        TrainHParams(warmup_iters=2, cosine_cycle_iters=30),
        LoopConfig(
            steps=12,
            batch_size=8,
            log_every=4,
            eval_every=1000,
            checkpoint_every=1000,
            parallel="sp",
            mesh_axes={"data": 2, "seq": 4},
        ),
        train_data=data,
        log_fn=lambda *_: None,
    )
    assert summary["history"][-1]["loss"] < summary["history"][0]["loss"]


def test_moe_expert_weights_sharded_on_expert_axis():
    from bpe_transformer_tpu.parallel import param_specs
    from jax.sharding import PartitionSpec as P

    cfg = MOE_CFG
    params = init_params(jax.random.PRNGKey(0), cfg)
    mesh = make_mesh({"data": 2, "expert": 4})
    specs = param_specs(params, mesh, "dp_ep")
    ffn = specs["layers"][0]["ffn"]
    assert ffn["w1"][0] == "expert"
    assert ffn["router"][0] == "expert"
    assert all(axis is None for axis in specs["layers"][0]["attn"]["q_proj"])


@pytest.mark.slow
def test_pp_moe_step_matches_single_device():
    """GPipe pipeline step with MoE FFNs == single-device step (aux weight
    zeroed for exact parity: the pp aux is per-microbatch/per-dispatch-group
    like sp; generous capacity so routing has no drops)."""
    from bpe_transformer_tpu.parallel.pp import (
        init_pp_opt_state,
        make_pp_train_step,
        shard_pp_params,
        stack_pipeline_params,
        unstack_pipeline_params,
    )

    cfg = dataclasses.replace(
        MOE_CFG,
        num_layers=4,
        capacity_factor=64.0,
        router_aux_weight=0.0,
    )
    hp = TrainHParams(warmup_iters=2, cosine_cycle_iters=10)
    params = init_params(jax.random.PRNGKey(0), cfg)
    opt_state = adamw_init(params)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(8, cfg.context_length)))
    y = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(8, cfg.context_length)))

    single = make_train_step(cfg, hp)
    p1, s1, m1 = single(params, opt_state, x, y)

    mesh = make_mesh({"data": 2, "pp": 4})
    pp_params = stack_pipeline_params(init_params(jax.random.PRNGKey(0), cfg), 4)
    pp_params = shard_pp_params(pp_params, mesh)
    opt2 = init_pp_opt_state(pp_params, mesh)
    step = make_pp_train_step(cfg, hp, mesh, num_microbatches=4)
    p2, s2, m2 = step(pp_params, opt2, x, y)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5
        ),
        p1,
        unstack_pipeline_params(jax.device_get(p2)),
    )


@pytest.mark.slow
def test_pp_moe_loop_trains():
    """The training loop accepts parallel="pp" with an MoE config (the
    second composition hole closed in round 2) and the loss decreases with
    the router aux ACTIVE."""
    from bpe_transformer_tpu.training.loop import LoopConfig, train

    cfg = dataclasses.replace(
        MOE_CFG, num_layers=4, capacity_factor=4.0, router_top_k=2
    )
    data = np.tile(np.arange(cfg.vocab_size, dtype=np.int32), 40)
    summary = train(
        cfg,
        TrainHParams(warmup_iters=2, cosine_cycle_iters=30),
        LoopConfig(
            steps=12,
            batch_size=8,
            log_every=4,
            eval_every=1000,
            checkpoint_every=1000,
            parallel="pp",
            mesh_axes={"data": 2, "pp": 4},
            pp_microbatches=4,
        ),
        train_data=data,
        log_fn=lambda *_: None,
    )
    assert summary["history"][-1]["loss"] < summary["history"][0]["loss"]


@pytest.mark.slow
def test_fsdp_ep_step_matches_single_device():
    """fsdp_ep: dense params sharded ZeRO-style over data while expert
    stacks shard over the expert axis — the full CLI strategy matrix row."""
    cfg = MOE_CFG
    hp = TrainHParams(warmup_iters=2, cosine_cycle_iters=10)
    params = init_params(jax.random.PRNGKey(0), cfg)
    opt_state = adamw_init(params)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(16, cfg.context_length)))
    y = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(16, cfg.context_length)))

    single = make_train_step(cfg, hp)
    p1, s1, m1 = single(params, opt_state, x, y)

    mesh = make_mesh({"data": 2, "expert": 4})
    params2 = init_params(jax.random.PRNGKey(0), cfg)
    params2 = shard_params(params2, mesh, "fsdp_ep")
    opt2 = adamw_init(params2)
    step = make_gspmd_train_step(cfg, hp, mesh, "fsdp_ep", example_params=params2)
    x2, y2 = shard_batch((x, y), mesh)
    p2, s2, m2 = step(params2, opt2, x2, y2)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5
        ),
        p1,
        jax.device_get(p2),
    )
