"""Checkpoint save/load roundtrips: params, optimizer state, iteration."""

import io

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bpe_transformer_tpu.checkpointing import load_checkpoint, save_checkpoint
from bpe_transformer_tpu.models import TS_TEST_CONFIG, forward, init_params
from bpe_transformer_tpu.optim import adamw_init, adamw_update


def _train_a_bit(params, state, steps=3):
    def loss_fn(p, ids):
        logits = forward(p, ids, TS_TEST_CONFIG)
        return logits.mean()

    ids = jnp.zeros((2, 8), dtype=jnp.int32)
    for _ in range(steps):
        grads = jax.grad(loss_fn)(params, ids)
        params, state = adamw_update(params, grads, state, lr=1e-3)
    return params, state


def _assert_trees_equal(a, b):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x), np.asarray(y)),
        a,
        b,
    )


def test_checkpoint_roundtrip_path(tmp_path):
    params = init_params(jax.random.PRNGKey(0), TS_TEST_CONFIG)
    state = adamw_init(params)
    params, state = _train_a_bit(params, state)

    path = tmp_path / "ckpt.pkl"
    save_checkpoint(path, params=params, opt_state=state, iteration=3)
    payload = load_checkpoint(path)

    assert payload["iteration"] == 3
    _assert_trees_equal(payload["params"], params)
    _assert_trees_equal(payload["opt_state"], state)


def test_checkpoint_roundtrip_filelike():
    params = {"w": jnp.arange(6.0).reshape(2, 3)}
    state = adamw_init(params)
    buf = io.BytesIO()
    save_checkpoint(buf, params=params, opt_state=state, iteration=17)
    buf.seek(0)
    payload = load_checkpoint(buf)
    assert payload["iteration"] == 17
    _assert_trees_equal(payload["params"], params)


def test_checkpoint_resume_continues_identically(tmp_path):
    """Train 3 steps, checkpoint, train 3 more; reload + 3 must match."""
    params = init_params(jax.random.PRNGKey(1), TS_TEST_CONFIG)
    state = adamw_init(params)
    params, state = _train_a_bit(params, state, steps=3)
    save_checkpoint(tmp_path / "mid.pkl", params=params, opt_state=state, iteration=3)

    final_params, _ = _train_a_bit(params, state, steps=3)

    payload = load_checkpoint(tmp_path / "mid.pkl")
    from bpe_transformer_tpu.optim.adamw import AdamWState

    restored_state = AdamWState(*payload["opt_state"])
    resumed_params, _ = _train_a_bit(payload["params"], restored_state, steps=3)
    _assert_trees_equal(final_params, resumed_params)


def test_checkpoint_extra_metadata(tmp_path):
    save_checkpoint(
        tmp_path / "c.pkl",
        params={"w": jnp.ones(2)},
        iteration=5,
        extra={"val_loss": 1.25, "config": {"d_model": 64}},
    )
    payload = load_checkpoint(tmp_path / "c.pkl")
    assert payload["extra"]["val_loss"] == 1.25
    assert payload["opt_state"] is None


# ----------------------------------------------- sharded directory format


def _fsdp_state():
    from bpe_transformer_tpu.parallel import make_mesh, shard_params

    mesh = make_mesh({"data": 8})
    params = init_params(jax.random.PRNGKey(0), TS_TEST_CONFIG)
    params = shard_params(params, mesh, "fsdp")
    state = adamw_init(params)
    return mesh, params, state


def test_sharded_checkpoint_roundtrip_fsdp(tmp_path):
    """An fsdp-sharded train state round-trips through the streaming
    directory format: per-shard files on disk (never one full-tree buffer),
    exact values back."""
    from bpe_transformer_tpu.checkpointing import (
        load_checkpoint_sharded,
        save_checkpoint_sharded,
    )

    _, params, state = _fsdp_state()
    ckpt = tmp_path / "step_8.ckpt"
    save_checkpoint_sharded(ckpt, params=params, opt_state=state, iteration=8)

    # The directory really is per-shard: sharded leaves produced multiple
    # .npy files, and no pickle holds array data (treedef.pkl is structure
    # only — far smaller than the parameters).
    import json

    manifest = json.loads((ckpt / "manifest.json").read_text())
    sharded_leaves = [r for r in manifest["leaves"] if "shards" in r]
    assert sharded_leaves, "no leaf was saved shard-wise"
    assert len(list(ckpt.glob(f"{sharded_leaves[0]['name']}.*.npy"))) > 1
    param_bytes = sum(
        np.prod(r["shape"], dtype=np.int64) * 4 for r in manifest["leaves"]
    )
    assert (ckpt / "treedef.pkl").stat().st_size < param_bytes // 10

    payload = load_checkpoint_sharded(ckpt)
    assert payload["iteration"] == 8
    _assert_trees_equal(payload["params"], params)
    _assert_trees_equal(payload["opt_state"], state)


def test_sharded_checkpoint_resume_replacement(tmp_path):
    """Loading with a shardings tree places every leaf straight onto its
    mesh sharding (resume re-placement), and load_checkpoint auto-detects
    the directory format."""
    from bpe_transformer_tpu.checkpointing import (
        load_checkpoint_sharded,
        save_checkpoint_sharded,
    )
    from bpe_transformer_tpu.parallel.sharding import param_shardings

    mesh, params, state = _fsdp_state()
    ckpt = tmp_path / "ck.ckpt"
    save_checkpoint_sharded(ckpt, params=params, opt_state=state, iteration=1)

    shardings = {
        "params": param_shardings(params, mesh, "fsdp"),
        "opt_state": type(state)(
            step=jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec()),
            m=param_shardings(state.m, mesh, "fsdp"),
            v=param_shardings(state.v, mesh, "fsdp"),
        ),
    }
    payload = load_checkpoint_sharded(ckpt, shardings=shardings)
    leaf = payload["params"]["token_embeddings"]
    assert isinstance(leaf, jax.Array)
    assert leaf.sharding == shardings["params"]["token_embeddings"]
    _assert_trees_equal(payload["params"], params)

    auto = load_checkpoint(ckpt)
    assert auto["iteration"] == 1
    _assert_trees_equal(auto["params"], params)


def test_loop_fsdp_uses_sharded_checkpoints_and_resumes(tmp_path):
    """The training loop writes directory checkpoints under fsdp and resumes
    from them bit-exactly."""
    from bpe_transformer_tpu.models.config import ModelConfig
    from bpe_transformer_tpu.training.loop import LoopConfig, train
    from bpe_transformer_tpu.training.train_step import TrainHParams

    cfg = ModelConfig(
        vocab_size=256, context_length=16, d_model=32,
        num_layers=2, num_heads=2, d_ff=64,
    )
    rng = np.random.default_rng(0)
    data = rng.integers(0, cfg.vocab_size, size=10_000, dtype=np.int32)
    loop_kwargs = dict(
        batch_size=8, log_every=2, eval_every=1000,
        parallel="fsdp", mesh_axes={"data": 8},
        checkpoint_dir=str(tmp_path / "ckpts"),
    )
    hp = TrainHParams(warmup_iters=2, cosine_cycle_iters=20)

    train(cfg, hp, LoopConfig(steps=4, checkpoint_every=4, **loop_kwargs),
          train_data=data, log_fn=lambda *_: None)
    ckpt = tmp_path / "ckpts" / "step_00000004.ckpt"
    assert ckpt.is_dir() and (ckpt / "manifest.json").exists()
    latest = tmp_path / "ckpts" / "latest.ckpt"
    assert latest.is_symlink()

    s_resumed = train(
        cfg, hp, LoopConfig(steps=8, checkpoint_every=4, **loop_kwargs),
        train_data=data, resume_from=str(latest), log_fn=lambda *_: None,
    )
    s_straight = train(
        cfg, hp,
        LoopConfig(steps=8, checkpoint_every=8, batch_size=8, log_every=2,
                   eval_every=1000, parallel="fsdp", mesh_axes={"data": 8},
                   checkpoint_dir=str(tmp_path / "ckpts2")),
        train_data=data, log_fn=lambda *_: None,
    )
    assert s_resumed["final_train_loss"] == pytest.approx(
        s_straight["final_train_loss"], rel=1e-5
    )


def test_async_checkpointer_roundtrip(tmp_path):
    """Background writes land the same bytes as sync saves, on_complete runs
    after the checkpoint exists, and wait() surfaces write errors."""
    from bpe_transformer_tpu.checkpointing import AsyncCheckpointer

    params = init_params(jax.random.PRNGKey(0), TS_TEST_CONFIG)
    state = adamw_init(params)
    saver = AsyncCheckpointer()

    seen = []
    path = tmp_path / "a.ckpt"
    saver.save(
        path, params=params, opt_state=state, iteration=5,
        on_complete=lambda: seen.append(path.exists()),
    )
    saver.wait()
    assert seen == [True]
    payload = load_checkpoint(path)
    assert payload["iteration"] == 5
    _assert_trees_equal(payload["params"], params)
    _assert_trees_equal(payload["opt_state"], state)

    # Sharded format through the same interface.
    _, sparams, sstate = _fsdp_state()
    sdir = tmp_path / "b.ckpt"
    saver.save(sdir, params=sparams, opt_state=sstate, iteration=7, sharded=True)
    saver.close()
    payload = load_checkpoint(sdir)
    assert payload["iteration"] == 7
    _assert_trees_equal(payload["params"], sparams)

    # A failing write is re-raised at the next wait(), not swallowed.
    saver.save(tmp_path / "nope" / "\0bad", params=params, iteration=1)
    with pytest.raises(BaseException):
        saver.wait()


def test_loop_async_checkpoint_resumable(tmp_path):
    """async_checkpoint=True: the final checkpoint is joined at loop exit
    and resumes bit-exact like the sync path."""
    from bpe_transformer_tpu.models.config import ModelConfig
    from bpe_transformer_tpu.training.loop import LoopConfig, train
    from bpe_transformer_tpu.training.train_step import TrainHParams

    cfg = ModelConfig(vocab_size=256, context_length=16, d_model=32,
                      num_layers=2, num_heads=2, d_ff=64)
    rng = np.random.default_rng(0)
    data = rng.integers(0, cfg.vocab_size, size=10_000, dtype=np.int32)
    hp = TrainHParams(warmup_iters=2, cosine_cycle_iters=20)
    lk = dict(batch_size=8, log_every=2, eval_every=1000,
              checkpoint_dir=str(tmp_path / "ck"), async_checkpoint=True)

    train(cfg, hp, LoopConfig(steps=4, checkpoint_every=4, **lk),
          train_data=data, log_fn=lambda *_: None)
    latest = tmp_path / "ck" / "latest.ckpt"
    assert latest.exists()

    resumed = train(cfg, hp, LoopConfig(steps=8, checkpoint_every=4, **lk),
                    train_data=data, resume_from=str(latest),
                    log_fn=lambda *_: None)
    straight = train(
        cfg, hp,
        LoopConfig(steps=8, checkpoint_every=8, batch_size=8, log_every=2,
                   eval_every=1000, checkpoint_dir=str(tmp_path / "ck2")),
        train_data=data, log_fn=lambda *_: None,
    )
    assert resumed["final_train_loss"] == pytest.approx(
        straight["final_train_loss"], rel=1e-5
    )


def test_sharded_checkpoint_reshard_to_different_mesh(tmp_path):
    """A checkpoint saved under one sharding layout loads onto ANOTHER
    (fsdp 8-way -> fsdp_tp 4x2): leaves reassemble from shard files and
    re-place onto the new mesh — elastic resharding."""
    from bpe_transformer_tpu.checkpointing import (
        load_checkpoint_sharded,
        save_checkpoint_sharded,
    )
    from bpe_transformer_tpu.parallel import make_mesh, shard_params
    from bpe_transformer_tpu.parallel.sharding import param_shardings

    _, params, state = _fsdp_state()  # fsdp over {"data": 8}
    ckpt = tmp_path / "reshard.ckpt"
    save_checkpoint_sharded(ckpt, params=params, opt_state=state, iteration=3)

    mesh2 = make_mesh({"data": 4, "model": 2})
    target = param_shardings(params, mesh2, "fsdp_tp")
    payload = load_checkpoint_sharded(
        ckpt,
        shardings={
            "params": target,
            "opt_state": type(state)(
                step=jax.sharding.NamedSharding(
                    mesh2, jax.sharding.PartitionSpec()
                ),
                m=target,
                v=target,
            ),
        },
    )
    leaf = payload["params"]["layers"][0]["attn"]["q_proj"]
    assert leaf.sharding == target["layers"][0]["attn"]["q_proj"]
    _assert_trees_equal(payload["params"], params)
    _assert_trees_equal(payload["opt_state"], state)


def test_sharded_checkpoint_incomplete_manifest_rejected(tmp_path):
    """A manifest whose shard boxes don't tile a leaf (e.g. written by one
    process of a multi-process mesh) must refuse to load rather than return
    uninitialized memory in the uncovered ranges."""
    import json

    from bpe_transformer_tpu.checkpointing import (
        load_checkpoint_sharded,
        save_checkpoint_sharded,
    )

    _, params, state = _fsdp_state()
    ckpt = tmp_path / "gap.ckpt"
    save_checkpoint_sharded(ckpt, params=params, opt_state=state, iteration=1)

    manifest = json.loads((ckpt / "manifest.json").read_text())
    victim = next(r for r in manifest["leaves"] if "shards" in r)
    victim["shards"] = victim["shards"][:-1]  # coverage gap
    (ckpt / "manifest.json").write_text(json.dumps(manifest))

    with pytest.raises(ValueError, match="cover|incomplete"):
        load_checkpoint_sharded(ckpt)


def test_sharded_checkpoint_orphan_recovery(tmp_path):
    """A hard crash inside the displace->replace window strands the old
    checkpoint in a `<name>.old*/d` sibling; loading the original path (via
    the public auto-detecting entry) must recover it, PROMOTE it back to the
    original path, and clean up the orphan."""
    from bpe_transformer_tpu.checkpointing import (
        load_checkpoint,
        save_checkpoint_sharded,
    )

    _, params, state = _fsdp_state()
    ckpt = tmp_path / "crashy.ckpt"
    save_checkpoint_sharded(ckpt, params=params, opt_state=state, iteration=7)

    displaced = tmp_path / "crashy.ckpt.old123xyz"
    displaced.mkdir()
    (displaced / ".bt_displaced").touch()  # the save machinery's marker
    (ckpt).rename(displaced / "d")  # simulate the crash window

    payload = load_checkpoint(ckpt)
    assert payload["iteration"] == 7
    _assert_trees_equal(payload["params"], params)
    assert (ckpt / "manifest.json").exists()  # promoted back into place
    assert not list(tmp_path.glob("crashy.ckpt.old*"))  # orphan reclaimed


def test_sharded_checkpoint_unmarked_old_sibling_untouched(tmp_path):
    """A user's manual `cp -r x.ckpt x.ckpt.old` backup (no ownership
    marker) must be neither deleted by a later save nor loaded as an
    orphan."""
    import shutil

    from bpe_transformer_tpu.checkpointing import save_checkpoint_sharded
    from bpe_transformer_tpu.checkpointing.checkpoint import (
        sharded_checkpoint_exists,
    )

    _, params, state = _fsdp_state()
    ckpt = tmp_path / "backed.ckpt"
    save_checkpoint_sharded(ckpt, params=params, opt_state=state, iteration=1)

    backup = tmp_path / "backed.ckpt.old"
    shutil.copytree(ckpt, backup / "d")  # user-made, no marker

    save_checkpoint_sharded(ckpt, params=params, opt_state=state, iteration=2)
    assert (backup / "d" / "manifest.json").exists()  # backup survives

    shutil.rmtree(ckpt)  # intentional delete: backup must NOT resurrect
    assert not sharded_checkpoint_exists(ckpt)
    with pytest.raises(FileNotFoundError):
        load_checkpoint(ckpt)


def test_sharded_checkpoint_failed_swap_restores_old(tmp_path, monkeypatch):
    """If the final directory swap raises, the previous checkpoint must be
    renamed back into place (not stranded in a temp sibling)."""
    import os as os_mod

    from bpe_transformer_tpu.checkpointing import (
        load_checkpoint_sharded,
        save_checkpoint_sharded,
    )

    _, params, state = _fsdp_state()
    ckpt = tmp_path / "swap.ckpt"
    save_checkpoint_sharded(ckpt, params=params, opt_state=state, iteration=1)

    real_replace = os_mod.replace

    def failing_replace(src, dst):
        if str(dst) == str(ckpt):
            raise OSError("simulated swap failure")
        return real_replace(src, dst)

    monkeypatch.setattr(os_mod, "replace", failing_replace)
    with pytest.raises(OSError, match="simulated swap failure"):
        save_checkpoint_sharded(ckpt, params=params, opt_state=state, iteration=2)
    monkeypatch.undo()

    payload = load_checkpoint_sharded(ckpt)  # the OLD checkpoint survives
    assert payload["iteration"] == 1
    _assert_trees_equal(payload["params"], params)
    # No stranded displaced copies remain.
    assert not list(tmp_path.glob("swap.ckpt.old*"))
