"""Checkpoint save/load roundtrips: params, optimizer state, iteration."""

import io

import numpy as np

import jax
import jax.numpy as jnp

from bpe_transformer_tpu.checkpointing import load_checkpoint, save_checkpoint
from bpe_transformer_tpu.models import TS_TEST_CONFIG, forward, init_params
from bpe_transformer_tpu.optim import adamw_init, adamw_update


def _train_a_bit(params, state, steps=3):
    def loss_fn(p, ids):
        logits = forward(p, ids, TS_TEST_CONFIG)
        return logits.mean()

    ids = jnp.zeros((2, 8), dtype=jnp.int32)
    for _ in range(steps):
        grads = jax.grad(loss_fn)(params, ids)
        params, state = adamw_update(params, grads, state, lr=1e-3)
    return params, state


def _assert_trees_equal(a, b):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x), np.asarray(y)),
        a,
        b,
    )


def test_checkpoint_roundtrip_path(tmp_path):
    params = init_params(jax.random.PRNGKey(0), TS_TEST_CONFIG)
    state = adamw_init(params)
    params, state = _train_a_bit(params, state)

    path = tmp_path / "ckpt.pkl"
    save_checkpoint(path, params=params, opt_state=state, iteration=3)
    payload = load_checkpoint(path)

    assert payload["iteration"] == 3
    _assert_trees_equal(payload["params"], params)
    _assert_trees_equal(payload["opt_state"], state)


def test_checkpoint_roundtrip_filelike():
    params = {"w": jnp.arange(6.0).reshape(2, 3)}
    state = adamw_init(params)
    buf = io.BytesIO()
    save_checkpoint(buf, params=params, opt_state=state, iteration=17)
    buf.seek(0)
    payload = load_checkpoint(buf)
    assert payload["iteration"] == 17
    _assert_trees_equal(payload["params"], params)


def test_checkpoint_resume_continues_identically(tmp_path):
    """Train 3 steps, checkpoint, train 3 more; reload + 3 must match."""
    params = init_params(jax.random.PRNGKey(1), TS_TEST_CONFIG)
    state = adamw_init(params)
    params, state = _train_a_bit(params, state, steps=3)
    save_checkpoint(tmp_path / "mid.pkl", params=params, opt_state=state, iteration=3)

    final_params, _ = _train_a_bit(params, state, steps=3)

    payload = load_checkpoint(tmp_path / "mid.pkl")
    from bpe_transformer_tpu.optim.adamw import AdamWState

    restored_state = AdamWState(*payload["opt_state"])
    resumed_params, _ = _train_a_bit(payload["params"], restored_state, steps=3)
    _assert_trees_equal(final_params, resumed_params)


def test_checkpoint_extra_metadata(tmp_path):
    save_checkpoint(
        tmp_path / "c.pkl",
        params={"w": jnp.ones(2)},
        iteration=5,
        extra={"val_loss": 1.25, "config": {"d_model": 64}},
    )
    payload = load_checkpoint(tmp_path / "c.pkl")
    assert payload["extra"]["val_loss"] == 1.25
    assert payload["opt_state"] is None
