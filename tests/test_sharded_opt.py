"""ZeRO-1 sharded optimizer + step overlap (optim/sharded.py, PR 7).

Pins the cross-replica sharded weight update (Xu et al. arXiv:2004.13336)
against the unsharded paths on the virtual 8-device CPU mesh: the dp
reduce-scatter/all-gather step and the GSPMD annotation variant must match
the replicated-optimizer numerics exactly, per-chip optimizer bytes must
scale ~1/N, checkpoints must round-trip through the PR-5 integrity path in
BOTH layouts (including a pre-sharding checkpoint resuming into a sharded
run), and the host→device prefetcher must change timings only — never
batches.
"""

import dataclasses
import json
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from bpe_transformer_tpu.models import TS_TEST_CONFIG, init_params
from bpe_transformer_tpu.optim import (
    AdamWState,
    ShardedAdamWState,
    adamw_init,
    adamw_update,
    restore_opt_state,
    shard_opt_state,
    sharded_adamw_init,
    sharded_adamw_update,
    unshard_opt_state,
)
from bpe_transformer_tpu.optim.sharded import flat_total, flatten_f32, unflatten_like
from bpe_transformer_tpu.parallel import (
    make_dp_train_step,
    make_gspmd_train_step,
    make_mesh,
    shard_batch,
    shard_params,
    zero1_opt_specs,
)
from bpe_transformer_tpu.telemetry import tree_bytes_per_device
from bpe_transformer_tpu.training.train_step import TrainHParams, make_train_step

CFG = dataclasses.replace(TS_TEST_CONFIG, vocab_size=512)
HP = TrainHParams(warmup_iters=2, cosine_cycle_iters=10)


def _setup(seed=0, batch=16):
    params = init_params(jax.random.PRNGKey(seed), CFG)
    rng = np.random.default_rng(seed)
    x = rng.integers(0, CFG.vocab_size, size=(batch, CFG.context_length))
    y = rng.integers(0, CFG.vocab_size, size=(batch, CFG.context_length))
    return params, jnp.asarray(x), jnp.asarray(y)


def _assert_trees_close(a, b, atol=2e-5):
    jax.tree_util.tree_map(
        lambda u, v: np.testing.assert_allclose(
            np.asarray(u), np.asarray(v), atol=atol
        ),
        a,
        b,
    )


# ------------------------------------------------------- flat-layout helpers


def test_flatten_unflatten_roundtrip():
    params, _, _ = _setup()
    total = flat_total(params)
    flat = flatten_f32(params, pad_to=total + 13)
    assert flat.shape == (total + 13,)
    restored = unflatten_like(flat, params)
    _assert_trees_close(params, restored, atol=0)


def test_shard_unshard_roundtrip():
    """dense -> ZeRO-1 -> dense is the identity (padding trimmed), for a
    non-trivial state (one real update so m/v are non-zero)."""
    params, x, y = _setup()
    step = make_train_step(CFG, HP)
    p1, opt, _ = step(params, adamw_init(params), x, y)
    sharded = shard_opt_state(opt, p1, n_shards=8)
    assert sharded.m.shape[0] == 8
    # The master is always materialized (also for f32 params — re-slicing
    # the replicated params per step would cost a full flat copy): its
    # flat view must equal the params exactly.
    total = flat_total(p1)
    np.testing.assert_array_equal(
        np.asarray(sharded.master).reshape(-1)[:total],
        np.asarray(flatten_f32(p1)),
    )
    dense = unshard_opt_state(sharded, p1)
    assert int(dense.step) == int(opt.step)
    _assert_trees_close(opt.m, dense.m, atol=0)
    _assert_trees_close(opt.v, dense.v, atol=0)


def test_restore_opt_state_all_crossings():
    params, x, y = _setup()
    step = make_train_step(CFG, HP)
    p1, opt, _ = step(params, adamw_init(params), x, y)
    # None -> fresh init in either mode.
    assert isinstance(restore_opt_state(None, p1), AdamWState)
    fresh = restore_opt_state(None, p1, zero1_shards=4)
    assert isinstance(fresh, ShardedAdamWState) and fresh.m.shape[0] == 4
    # dense payload -> sharded (legacy checkpoint into a zero1 run).
    sharded = restore_opt_state(tuple(opt), p1, zero1_shards=8)
    assert isinstance(sharded, ShardedAdamWState)
    # sharded payload -> DIFFERENT width (save on 8, resume on 4).
    rewidth = restore_opt_state(tuple(sharded), p1, zero1_shards=4)
    assert rewidth.m.shape[0] == 4
    _assert_trees_close(
        unshard_opt_state(rewidth, p1).m, opt.m, atol=0
    )
    # sharded payload -> dense (zero1 checkpoint into an unsharded run).
    dense = restore_opt_state(tuple(sharded), p1)
    assert isinstance(dense, AdamWState)
    _assert_trees_close(dense.v, opt.v, atol=0)
    # Cross-width resume preserves the fp32 MASTER bits exactly (bf16
    # params): the accumulated sub-bf16 precision must survive, not be
    # re-derived from the rounded params.
    bf16_params = jax.tree_util.tree_map(
        lambda p: p.astype(jnp.bfloat16), p1
    )
    with_master = shard_opt_state(opt, bf16_params, n_shards=8)
    # Perturb master below bf16 resolution to distinguish it from a
    # params-derived rebuild.
    delta = 1e-4
    with_master = with_master._replace(master=with_master.master + delta)
    rewidth_m = restore_opt_state(tuple(with_master), bf16_params, zero1_shards=4)
    assert rewidth_m.master is not None
    total = flat_total(bf16_params)
    np.testing.assert_allclose(
        np.asarray(rewidth_m.master).reshape(-1)[:total],
        np.asarray(with_master.master).reshape(-1)[:total],
        atol=0,
    )


# ------------------------------------------------------------ step parity


@pytest.mark.slow
def test_zero1_dp_step_matches_plain_dp():
    """The reduce-scatter/all-gather update reproduces the pmean+replicated
    AdamW step exactly, and per-chip optimizer bytes drop ~1/N."""
    mesh = make_mesh({"data": 8})
    params, x, y = _setup()
    xb, yb = shard_batch((x, y), mesh)

    plain = make_dp_train_step(CFG, HP, mesh)
    opt_plain = adamw_init(params)
    plain_bytes = tree_bytes_per_device(opt_plain)
    p1, s1, m1 = plain(params, opt_plain, xb, yb)

    params2, _, _ = _setup()
    opt2 = sharded_adamw_init(params2, 8, mesh=mesh)
    zero1_bytes = tree_bytes_per_device(opt2)
    step = make_dp_train_step(CFG, HP, mesh, opt_sharding="zero1")
    p2, s2, m2 = step(params2, opt2, xb, yb)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-6)
    np.testing.assert_allclose(
        float(m1["grad_norm"]), float(m2["grad_norm"]), rtol=1e-5
    )
    _assert_trees_close(jax.device_get(p1), jax.device_get(p2))
    # The memory claim: m/v/master each 1/8 per chip — (8P+4P)/8 against
    # the dense state's 8P, i.e. ratio 0.1875 (+ step scalar + pad tail).
    assert zero1_bytes < plain_bytes * 0.25
    # The moments really live sharded (one (1, L) block per device).
    assert s2.m.sharding.shard_shape(s2.m.shape)[0] == 1
    # Second step: the sharded state threads through (bias correction,
    # moments) identically.
    p1, s1, m1 = plain(p1, s1, xb, yb)
    p2, s2, m2 = step(p2, s2, xb, yb)
    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-6)
    _assert_trees_close(jax.device_get(p1), jax.device_get(p2))


def test_zero1_gspmd_step_matches_single_device():
    """GSPMD variant: zero1 NamedSharding annotations on m/v leave the math
    untouched while the persisted moments shard 1/N."""
    params, x, y = _setup()
    single = make_train_step(CFG, HP)
    p1, s1, m1 = single(params, adamw_init(params), x, y)

    mesh = make_mesh({"data": 8})
    params2 = shard_params(init_params(jax.random.PRNGKey(0), CFG), mesh, "dp")
    opt2 = adamw_init(params2)
    from bpe_transformer_tpu.parallel import zero1_opt_shardings

    moment_sh = zero1_opt_shardings(params2, mesh, "dp")
    opt2 = AdamWState(
        step=opt2.step,
        m=jax.device_put(opt2.m, moment_sh),
        v=jax.device_put(opt2.v, moment_sh),
    )
    sharded_bytes = tree_bytes_per_device(opt2)
    step = make_gspmd_train_step(
        CFG, HP, mesh, "dp", example_params=params2, opt_sharding="zero1"
    )
    xb, yb = shard_batch((x, y), mesh)
    p2, s2, m2 = step(params2, opt2, xb, yb)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    _assert_trees_close(p1, jax.device_get(p2))
    # Memory: big moment leaves sharded 8-way; tiny norms stay replicated.
    assert sharded_bytes < tree_bytes_per_device(s1) * 0.2
    # Out-shardings keep the moments sharded after the step (the state that
    # persists between steps is what costs HBM).
    big_m = max(jax.tree_util.tree_leaves(s2.m), key=lambda l: l.size)
    assert int(np.prod(big_m.sharding.shard_shape(big_m.shape))) == big_m.size // 8


def test_zero1_specs_extend_only_unsharded_dims():
    params, _, _ = _setup()
    mesh = make_mesh({"data": 8})
    specs = zero1_opt_specs(params, mesh, "dp")
    flat = [
        s for s in jax.tree_util.tree_leaves(
            specs, is_leaf=lambda x: isinstance(x, P)
        )
    ]
    assert any("data" in spec for spec in flat)
    # fsdp is already data-sharded: extension is a no-op.
    from bpe_transformer_tpu.parallel import param_specs

    assert zero1_opt_specs(params, mesh, "fsdp") == param_specs(
        params, mesh, "fsdp"
    )


def test_zero1_master_weights_bf16_one_step_matches_dense():
    """bf16 params carry an fp32 master shard: the first update matches the
    dense bf16 AdamW exactly (same f32 starting point), and the master
    stays the f32 truth the next step reads."""
    mesh = make_mesh({"data": 8})
    params = {
        "w": jnp.asarray(
            np.random.default_rng(0).normal(size=(64, 16)), jnp.bfloat16
        ),
        "b": jnp.zeros((24,), jnp.bfloat16),
    }
    grads = {
        "w": jnp.asarray(
            np.random.default_rng(1).normal(size=(64, 16)) * 0.01, jnp.bfloat16
        ),
        "b": jnp.full((24,), 0.01, jnp.bfloat16),
    }
    state = sharded_adamw_init(params, 8, mesh=mesh)
    assert state.master is not None

    spec = ShardedAdamWState(step=P(), m=P("data"), v=P("data"), master=P("data"))

    def body(p, g, s):
        return sharded_adamw_update(
            p, g, s, 0.1, axis="data", n_shards=8, grad_clip_norm=1e9
        )

    stepped = jax.jit(
        jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(P(), P(), spec),
            out_specs=(P(), spec, P()),
            check_vma=False,
        )
    )
    new_p, new_s, norm = stepped(params, grads, state)

    ref_p, ref_s = adamw_update(params, grads, adamw_init(params), 0.1)
    _assert_trees_close(
        jax.device_get(new_p), jax.device_get(ref_p), atol=0
    )
    # The master shard holds the unrounded f32 params the next step reads
    # (the bf16 params are its rounded projection).
    total = flat_total(params)
    master_flat = np.asarray(jax.device_get(new_s.master)).reshape(-1)[:total]
    p_flat = np.concatenate(
        [
            np.asarray(l, np.float32).ravel()
            for l in jax.tree_util.tree_leaves(jax.device_get(new_p))
        ]
    )
    np.testing.assert_allclose(p_flat, master_flat, atol=1e-2)


# ----------------------------------------------------------- error surface


def test_zero1_rejects_unsupported_combinations():
    mesh = make_mesh({"data": 8})
    with pytest.raises(ValueError, match="unknown opt_sharding"):
        make_dp_train_step(CFG, HP, mesh, opt_sharding="zero3")
    with pytest.raises(ValueError, match="health/dynamics"):
        make_dp_train_step(CFG, HP, mesh, opt_sharding="zero1", health=True)

    from bpe_transformer_tpu.training.loop import LoopConfig, train

    data = np.tile(np.arange(CFG.vocab_size, dtype=np.int32), 40)
    base = dict(steps=2, batch_size=8, log_every=1, eval_every=100,
                checkpoint_every=100)
    with pytest.raises(ValueError, match="needs a data-parallel mesh"):
        train(CFG, HP, LoopConfig(opt_sharding="zero1", **base), data)
    with pytest.raises(ValueError, match="needs a data-parallel mesh"):
        train(
            CFG,
            HP,
            LoopConfig(opt_sharding="zero1", parallel="pp", **base),
            data,
        )
    with pytest.raises(ValueError, match='"data" axis'):
        train(
            CFG,
            HP,
            LoopConfig(
                opt_sharding="zero1", parallel="tp",
                mesh_axes={"model": 8}, **base,
            ),
            data,
        )
    with pytest.raises(ValueError, match="prefetch"):
        train(CFG, HP, LoopConfig(prefetch=-1, **base), data)


# -------------------------------------------------------- donation audit


@pytest.mark.slow
def test_train_step_donation_no_copies():
    """All three train-step variants donate params+opt-state (the update
    happens in place in device memory: inputs are invalidated), while the
    attribution probe's AOT copies deliberately do NOT donate."""
    params, x, y = _setup(batch=8)
    opt = adamw_init(params)
    step = make_train_step(CFG, HP)
    p1, s1, _ = step(params, opt, x, y)
    assert all(l.is_deleted() for l in jax.tree_util.tree_leaves(params))
    assert all(l.is_deleted() for l in jax.tree_util.tree_leaves(tuple(opt)))

    mesh = make_mesh({"data": 8})
    params, x, y = _setup(batch=8)
    xb, yb = shard_batch((x, y), mesh)
    opt = sharded_adamw_init(params, 8, mesh=mesh)
    zstep = make_dp_train_step(CFG, HP, mesh, opt_sharding="zero1")
    p2, s2, _ = zstep(params, opt, xb, yb)
    assert all(l.is_deleted() for l in jax.tree_util.tree_leaves(params))
    assert all(l.is_deleted() for l in jax.tree_util.tree_leaves(tuple(opt)))

    # The probe never invalidates the live training state.
    from bpe_transformer_tpu.telemetry.attribution import StepProbe

    probe = StepProbe(
        CFG, HP, batch_size=8, mesh=mesh, parallel="dp", opt_sharding="zero1"
    )
    record = probe.attribution_record(
        p2, s2, step=1, wall_step_s=0.1, t=0.0
    )
    assert not any(l.is_deleted() for l in jax.tree_util.tree_leaves(p2))
    assert not any(
        l.is_deleted() for l in jax.tree_util.tree_leaves(tuple(s2))
    )
    # ZeRO-1 interleaves its collectives like GSPMD: no made-up split.
    assert record["collective_frac"] is None
    assert probe.fetches_per_measure == StepProbe.FETCHES_PER_MEASURE


# ------------------------------------------------------------- prefetcher


def test_prefetcher_returns_identical_batches():
    from bpe_transformer_tpu.data import BatchPrefetcher

    calls = []

    def make(it):
        calls.append(it)
        return ("batch", it), 1

    pf = BatchPrefetcher(make, depth=2)
    try:
        got = pf.get(0)
        pf.schedule(1)
        pf.schedule(2)
        pf.schedule(2)  # duplicate: ignored
        assert got == (("batch", 0), 1)
        assert pf.get(1) == (("batch", 1), 1)
        assert pf.get(2) == (("batch", 2), 1)
        # A get for an unscheduled iteration builds synchronously.
        assert pf.get(7) == (("batch", 7), 1)
    finally:
        pf.close()
    assert sorted(calls) == [0, 1, 2, 7]


def test_prefetcher_invalidate_and_errors():
    from bpe_transformer_tpu.data import BatchPrefetcher

    def make(it):
        if it == 3:
            raise RuntimeError("injected read fault")
        return it

    pf = BatchPrefetcher(make, depth=1)
    try:
        pf.schedule(3)
        with pytest.raises(RuntimeError, match="injected read fault"):
            pf.get(3)
        # Default invalidate drains a poisoned pipeline without raising
        # (shutdown semantics)...
        pf.schedule(3)
        pf.invalidate()
        assert pf.get(5) == 5
        # ...but reraise=True surfaces a consumed worker fault — a
        # fire-once injected read fault must not vanish with the pipeline
        # (the rollback path uses this).
        pf.schedule(3)
        with pytest.raises(RuntimeError, match="injected read fault"):
            pf.invalidate(reraise=True)
    finally:
        pf.close()
    # depth=0 is fully synchronous (no worker thread).
    pf0 = BatchPrefetcher(make, depth=0)
    pf0.schedule(1)
    assert pf0.get(1) == 1
    pf0.close()
    with pytest.raises(ValueError):
        BatchPrefetcher(make, depth=-1)


def test_prefetcher_overlaps_build_with_consumer():
    from bpe_transformer_tpu.data import BatchPrefetcher

    built = threading.Event()

    def make(it):
        built.set()
        return it

    pf = BatchPrefetcher(make, depth=1)
    try:
        pf.schedule(0)
        # The worker builds WITHOUT a get() on the main thread.
        assert built.wait(timeout=5.0)
        assert pf.get(0) == 0
    finally:
        pf.close()


# ---------------------------------------------------------- loop end to end


def _loop_common(tmp_path, **overrides):
    from bpe_transformer_tpu.training.loop import LoopConfig

    base = dict(
        steps=4,
        batch_size=8,
        log_every=2,
        eval_every=1000,
        checkpoint_every=2,
        parallel="dp",
        mesh_axes={"data": 8},
        seed=0,
    )
    base.update(overrides)
    return LoopConfig(**base)


@pytest.mark.slow
def test_loop_zero1_end_to_end(tmp_path):
    """End to end through train() with prefetch on: resources records carry
    the ~1/N per-chip opt-state bytes (vs the dense state's, computed
    host-side — no second training run needed), and the sharded-state
    checkpoint verifies through the PR-5 integrity path and resumes.
    Loss parity vs the replicated-optimizer path is pinned exactly at the
    step level above; the loop-level trajectory identity (incl. a legacy
    dense checkpoint crossing into zero1) lives in the slow matrix."""
    from bpe_transformer_tpu.resilience.integrity import verify_checkpoint
    from bpe_transformer_tpu.training.loop import train

    data = np.tile(np.arange(CFG.vocab_size, dtype=np.int32), 40)
    zero1 = train(
        CFG, HP,
        _loop_common(
            tmp_path, opt_sharding="zero1", prefetch=2,
            checkpoint_dir=str(tmp_path / "z"),
            metrics_jsonl=str(tmp_path / "z.jsonl"),
        ),
        data, log_fn=lambda *_: None,
    )
    assert np.isfinite(zero1["final_train_loss"])

    resources = [
        r
        for r in (
            json.loads(l) for l in open(tmp_path / "z.jsonl") if l.strip()
        )
        if r.get("kind") == "resources"
    ]
    dense_bytes = tree_bytes_per_device(
        adamw_init(init_params(jax.random.PRNGKey(0), CFG))
    )
    # (m + v + fp32 master)/8 vs dense m + v: ratio 0.1875 (+ pad).
    assert resources[-1]["opt_state_bytes"] < dense_bytes * 0.25
    assert resources[-1]["params_bytes"] > 0

    # The sharded-opt-state checkpoint is CRC-verifiable and resumes.
    ckpt = tmp_path / "z" / "latest.ckpt"
    assert verify_checkpoint(ckpt).ok
    resumed = train(
        CFG, HP,
        _loop_common(
            tmp_path, steps=6, opt_sharding="zero1",
            checkpoint_dir=str(tmp_path / "z"),
        ),
        data, resume_from=str(tmp_path / "z"), log_fn=lambda *_: None,
    )
    assert resumed["history"][-1]["step"] == 6


@pytest.mark.slow
def test_loop_legacy_unsharded_checkpoint_resumes_into_zero1(tmp_path):
    """A pre-sharding (dense AdamWState) checkpoint resumes into a ZeRO-1
    run and continues on the EXACT trajectory of an uninterrupted sharded
    run — the conversion is numerically free.  (The conversion itself is
    tier-1 via test_restore_opt_state_all_crossings; this is the loop-level
    end-to-end, behind `slow` like the rest of the loop matrix.)"""
    from bpe_transformer_tpu.training.loop import train

    data = np.tile(np.arange(CFG.vocab_size, dtype=np.int32), 40)
    # Uninterrupted zero1 run: the reference trajectory.
    full = train(
        CFG, HP, _loop_common(tmp_path, steps=6, opt_sharding="zero1"),
        data, log_fn=lambda *_: None,
    )
    # Plain dp run leaves a dense checkpoint at step 4...
    train(
        CFG, HP,
        _loop_common(tmp_path, checkpoint_dir=str(tmp_path / "plain")),
        data, log_fn=lambda *_: None,
    )
    # ...which a zero1 run resumes and finishes.
    resumed = train(
        CFG, HP,
        _loop_common(
            tmp_path, steps=6, opt_sharding="zero1",
            checkpoint_dir=str(tmp_path / "plain"),
        ),
        data, resume_from=str(tmp_path / "plain"), log_fn=lambda *_: None,
    )
    assert resumed["final_train_loss"] == pytest.approx(
        full["final_train_loss"], rel=1e-6
    )


# --------------------------------------------------------- compile cache


def test_compile_cache_warm_restart_hits(tmp_path):
    """--compile-cache wiring, in the shape it actually runs in production
    (a respawned process): the first interpreter populates the persistent
    cache, the second is served from disk — its cache-hit counter climbs
    while the cold one's stays 0.  Subprocess-based on purpose:
    ``jax.clear_caches()`` mid-process destabilizes later donated
    executions on the CPU runtime, and warm *restart* is the claim anyway.
    """
    import os
    import subprocess
    import sys

    from conftest import REPO_ROOT

    script = (
        "import jax, jax.numpy as jnp\n"
        "from bpe_transformer_tpu.telemetry.resources import ("
        "compile_cache_hits, install_compile_counter)\n"
        "from bpe_transformer_tpu.utils.compile_cache import "
        "enable_compile_cache\n"
        "install_compile_counter()\n"
        f"enable_compile_cache({str(tmp_path / 'xla_cache')!r})\n"
        "jax.jit(lambda a: jnp.sin(a) @ jnp.cos(a).T)("
        "jnp.ones((16, 16))).block_until_ready()\n"
        "print('CACHE_HITS=', compile_cache_hits(), sep='')\n"
    )

    def run():
        proc = subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True,
            text=True,
            timeout=300,
            env={
                **os.environ,
                "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "",  # 1 host device: fast startup
            },
            cwd=str(REPO_ROOT),
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        line = next(
            l for l in proc.stdout.splitlines() if l.startswith("CACHE_HITS=")
        )
        return int(line.split("=")[1])

    cold_hits = run()
    assert cold_hits == 0
    entries = [p for p in (tmp_path / "xla_cache").rglob("*") if p.is_file()]
    assert entries, "persistent cache wrote no entries"
    warm_hits = run()
    assert warm_hits > 0


def test_cli_exposes_new_flags():
    from bpe_transformer_tpu.training.cli import build_parser

    parser = build_parser()
    args = parser.parse_args(
        [
            "train", "--data", "x.bin", "--parallel", "dp",
            "--opt-sharding", "zero1", "--prefetch", "2",
            "--compile-cache", "/tmp/cc",
        ]
    )
    assert args.opt_sharding == "zero1"
    assert args.prefetch == 2
    assert args.compile_cache == "/tmp/cc"
    serve = parser.parse_args(
        [
            "serve", "--checkpoint", "c.ckpt", "--tokenizer-dir", "tok",
            "--compile-cache", "/tmp/cc",
        ]
    )
    assert serve.compile_cache == "/tmp/cc"


# ------------------------------------------------------------- bench row


def test_bench_sharded_opt_stream_summary(tmp_path):
    from conftest import load_script_module

    bench = load_script_module(
        "bench_sharded_opt_test", "benchmarks/bench_sharded_opt.py"
    )
    stream = tmp_path / "s.jsonl"
    rows = [
        {"kind": "manifest", "run_kind": "train", "time_utc": "t", "host": "h"},
        {"step": 2, "loss": 1.0, "tokens_per_sec_per_chip": 100.0},
        {"step": 4, "loss": 0.9, "tokens_per_sec_per_chip": 120.0},
        {
            "kind": "resources", "time_unix": 0, "host_rss_bytes": 1,
            "live_buffer_bytes": 1, "compile_events": 1,
            "hbm_bytes_in_use": None, "hbm_peak_bytes_in_use": None,
            "hbm_bytes_limit": None, "opt_state_bytes": 1000,
            "params_bytes": 4000,
        },
        {
            "kind": "attribution", "t": 0, "step": 4, "wall_step_s": 0.1,
            "device_step_s": 0.09, "compute_frac": 0.8,
            "collective_frac": None, "host_gap_frac": 0.1,
        },
        {"kind": "footer", "t": 1, "record_counts": {}},
    ]
    with open(stream, "w") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")
    summary = bench.stream_summary(stream)
    assert summary["tokens_per_sec_per_chip"] == 110.0
    assert summary["opt_state_bytes"] == 1000
    assert summary["host_gap_frac"] == 0.1
    assert summary["collective_frac"] is None


@pytest.mark.slow
def test_loop_gspmd_zero1_sharded_checkpoint_roundtrip(tmp_path):
    """GSPMD + zero1: the streaming sharded-directory checkpoint records
    the 1/N moment shards, verifies through the integrity path, and the
    resume loader re-places them onto the zero1 shardings."""
    from bpe_transformer_tpu.resilience.integrity import verify_checkpoint
    from bpe_transformer_tpu.training.loop import train

    data = np.tile(np.arange(CFG.vocab_size, dtype=np.int32), 40)
    common = dict(parallel="fsdp", opt_sharding="zero1",
                  checkpoint_dir=str(tmp_path / "g"))
    train(
        CFG, HP, _loop_common(tmp_path, **common), data,
        log_fn=lambda *_: None,
    )
    assert verify_checkpoint(tmp_path / "g" / "latest.ckpt").ok
    resumed = train(
        CFG, HP, _loop_common(tmp_path, steps=6, **common), data,
        resume_from=str(tmp_path / "g"), log_fn=lambda *_: None,
    )
    assert resumed["history"][-1]["step"] == 6


# ------------------------------------------------------------ slow matrix


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["accum", "inner"])
def test_zero1_dp_stacked_modes_match_plain(mode):
    """grad-accum and inner-steps stacking compose with the sharded update:
    same numerics as the replicated-optimizer dp step in the same mode."""
    mesh = make_mesh({"data": 8})
    params, x, y = _setup()
    kwargs = (
        dict(accum_steps=2) if mode == "accum" else dict(inner_steps=2)
    )
    if mode == "accum":
        xs = x.reshape(2, 8, -1)
        ys = y.reshape(2, 8, -1)
    else:
        xs = jnp.stack([x, y.astype(x.dtype) % CFG.vocab_size])
        ys = jnp.stack([y, x.astype(y.dtype) % CFG.vocab_size])
    xb, yb = shard_batch((xs, ys), mesh, stacked=True)

    plain = make_dp_train_step(CFG, HP, mesh, **kwargs)
    p1, s1, m1 = plain(params, adamw_init(params), xb, yb)

    params2, _, _ = _setup()
    opt2 = sharded_adamw_init(params2, 8, mesh=mesh)
    step = make_dp_train_step(CFG, HP, mesh, opt_sharding="zero1", **kwargs)
    p2, s2, m2 = step(params2, opt2, xb, yb)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-6)
    _assert_trees_close(jax.device_get(p1), jax.device_get(p2))
