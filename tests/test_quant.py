"""int8 weight-quantized decode + fused sample-in-kernel (ISSUE 11).

Pins the three legs of the quantized-compute decode path:

* **per-channel int8 weights** (`ops/quant.py` + the dequant-in-register
  Pallas matmul): exact kernel-vs-XLA-reference parity, bounded
  dequantization error, the quantize-tree structure contract
  (embeddings/norms untouched, MoE refused), and the ~2x/4x weight-byte
  cut asserted via tree bytes (the PR 9 pool-bytes pin pattern);
* **fused sampling** (`kernels/pallas/sample.py`): the fused
  projection+filter+sample kernel is token-identical to the unfused
  `sample_tokens` chain across runtime knob mixes (the gumbel noise IS
  what `jax.random.categorical` draws), engine-level greedy AND sampled
  parity fused-vs-unfused, the spec-verify kernel against the
  `_spec_verify_program` reference math, and greedy spec parity on the
  fully quantized+fused path;
* **quality gates** (PR 9 style): quantized-vs-f32 decode logit
  max-abs-error bound, a greedy long-decode smoke, bounded-compile
  assertions (the quantized/fused ladder adds no unbounded programs),
  and the serving stats/statusz/metrics/roofline surfaces.
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bpe_transformer_tpu.models.config import ModelConfig
from bpe_transformer_tpu.models.decode import decode_step, init_kv_cache
from bpe_transformer_tpu.models.transformer import init_params, lm_head_weight
from bpe_transformer_tpu.ops.core import head_logits, linear
from bpe_transformer_tpu.ops.quant import (
    dequantize,
    is_quantized,
    quant_linear,
    quant_linear_xla,
    quantize_params,
    quantize_weight,
    tree_bytes,
)

REPO = Path(__file__).resolve().parent.parent

CFG = ModelConfig(
    vocab_size=128, context_length=64, d_model=32, num_layers=2,
    num_heads=4, d_ff=48,
)
CFG_GQA = ModelConfig(
    vocab_size=96, context_length=32, d_model=32, num_layers=2,
    num_heads=4, num_kv_heads=2, d_ff=40,
)


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


def _drain(engine, prompts, *, temp=0.0, top_k=None, top_p=None,
           max_new_tokens=8):
    toks = {}
    for i, p in enumerate(prompts):
        ev = engine.admit(
            p, max_new_tokens=max_new_tokens, temperature=temp,
            top_k=top_k, top_p=top_p, seed=11 + i,
        )
        toks.setdefault(ev.slot, []).append(ev.token)
    while engine.active_count:
        for ev in engine.tick():
            toks.setdefault(ev.slot, []).append(ev.token)
    return toks


# ------------------------------------------------------------ quantization


def test_quantize_weight_layout_and_error_bound():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(83, 64)).astype(np.float32)) * 0.1
    wq = quantize_weight(w)
    assert is_quantized(wq)
    assert wq["q"].dtype == jnp.int8 and wq["q"].shape == w.shape
    assert wq["scale"].dtype == jnp.float32 and wq["scale"].shape == (83,)
    # Per-channel symmetric quantization: error <= scale/2 per channel.
    err = jnp.abs(dequantize(wq) - w)
    assert float(jnp.max(err - wq["scale"][:, None] / 2)) <= 1e-7
    # An all-zero row dequantizes to exact zeros (scale 0, no NaN).
    w0 = quantize_weight(w.at[5].set(0.0))
    assert float(jnp.abs(dequantize(w0)[5]).max()) == 0.0


@pytest.mark.parametrize("shape", [(8, 683, 256), (3, 97, 64), (1, 40, 32)])
def test_quant_matmul_kernel_matches_xla_reference(shape):
    """The Pallas dequant-in-register matmul equals the XLA reference
    bitwise-close on every block layout (odd d_out falls back to the
    whole-array tile)."""
    m, o, i = shape
    rng = np.random.default_rng(1)
    wq = quantize_weight(
        jnp.asarray(rng.normal(size=(o, i)).astype(np.float32))
    )
    x = jnp.asarray(rng.normal(size=(m, i)).astype(np.float32))
    np.testing.assert_allclose(
        np.asarray(quant_linear(x, wq)),
        np.asarray(quant_linear_xla(x, wq)),
        rtol=0, atol=1e-5,
    )


def test_linear_and_head_dispatch_on_quantized_dicts():
    rng = np.random.default_rng(2)
    w = jnp.asarray(rng.normal(size=(96, 64)).astype(np.float32))
    wq = quantize_weight(w)
    x = jnp.asarray(rng.normal(size=(2, 3, 64))).astype(jnp.bfloat16)
    out = linear(x, wq)
    assert out.shape == (2, 3, 96) and out.dtype == jnp.bfloat16
    logits = head_logits(x, wq)
    # head_logits contract: logits stay float32-clean under quantization.
    assert logits.dtype == jnp.float32
    ref = head_logits(x.astype(jnp.float32), w)
    assert float(jnp.max(jnp.abs(logits - ref))) < 0.2


def test_quantize_params_structure_and_bytes(params):
    qparams = quantize_params(params, CFG)
    # Embeddings and norm gains pass through IDENTICALLY (same arrays).
    assert qparams["token_embeddings"] is params["token_embeddings"]
    assert qparams["ln_final"] is params["ln_final"]
    layer = qparams["layers"][0]
    assert layer["ln1"] is params["layers"][0]["ln1"]
    for name in ("q_proj", "k_proj", "v_proj", "output_proj"):
        assert is_quantized(layer["attn"][name])
    for name in ("w1", "w2", "w3"):
        assert is_quantized(layer["ffn"][name])
    assert is_quantized(qparams["lm_head"])
    # The matmul-weight bytes shrink ~4x vs f32 (scale overhead included).
    dense = tree_bytes(params["layers"]) + tree_bytes(params["lm_head"])
    quant = tree_bytes(qparams["layers"]) + tree_bytes(qparams["lm_head"])
    assert quant < 0.30 * dense
    # MoE expert stacks are NOT covered: refuse loudly.
    moe_cfg = ModelConfig(
        vocab_size=64, context_length=16, d_model=16, num_layers=1,
        num_heads=2, d_ff=32, ffn_type="moe", n_experts=2,
    )
    with pytest.raises(ValueError, match="[Mm]o[Ee]"):
        quantize_params(init_params(jax.random.PRNGKey(1), moe_cfg), moe_cfg)


def test_quantized_decode_logit_error_bound(params):
    """QUALITY GATE: int8-weight decode logits stay within a documented
    max-abs-error bound of the f32 path (PR 9's kv-int8 pattern)."""
    qparams = quantize_params(params, CFG)
    lm_head = lm_head_weight(params, CFG)
    q_head = quantize_weight(lm_head)
    cache = init_kv_cache(CFG, 3)
    token = jnp.asarray([5, 9, 77], jnp.int32)
    pos = jnp.zeros(3, jnp.int32)
    ref, _ = decode_step(params, token, pos, cache, CFG, lm_head=lm_head)
    got, _ = decode_step(qparams, token, pos, cache, CFG, lm_head=q_head)
    err = float(jnp.max(jnp.abs(got - ref)))
    assert err < 0.15, f"int8-weight logit error {err} over bound"
    assert err > 0  # the paths genuinely differ — the bound is load-bearing


# ---------------------------------------------------------- fused sampling


def _knob_rows():
    temps = jnp.asarray([0.0, 1.0, 0.7, 1.3, 1.0, 0.5], jnp.float32)
    top_ks = jnp.asarray([0, 0, 5, 1, 40, 0], jnp.int32)
    top_ps = jnp.asarray([2.0, 0.9, 2.0, 0.5, 0.3, 0.0], jnp.float32)
    return temps, top_ks, top_ps


@pytest.mark.parametrize("quantized", [False, True])
def test_fused_head_sample_token_identical_to_unfused(quantized):
    """ACCEPTANCE: the fused projection+filter+sample kernel emits the
    SAME tokens as the unfused head_logits -> filter_logits ->
    categorical chain across greedy/temp/top-k/top-p knob mixes — the
    gumbel noise is exactly what categorical would draw from the same
    keys."""
    from bpe_transformer_tpu.kernels.pallas.sample import fused_head_sample
    from bpe_transformer_tpu.serving.engine import gumbel_rows, sample_tokens

    rng = np.random.default_rng(3)
    s, d, v = 6, 64, 257  # odd vocab: whole-V block path
    hidden = jnp.asarray(rng.normal(size=(s, d)).astype(np.float32))
    head = jnp.asarray(rng.normal(size=(v, d)).astype(np.float32)) * 0.3
    if quantized:
        head = quantize_weight(head)
    temps, top_ks, top_ps = _knob_rows()
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(s))
    ref = sample_tokens(
        head_logits(hidden, head), keys, temps, top_ks, top_ps
    )
    tok = fused_head_sample(
        hidden, head, temps, top_ks, top_ps, gumbel_rows(keys, v)
    )
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(tok))


def test_fused_verify_head_matches_reference_math():
    """The spec-verify kernel's three outputs (greedy, p(d), residual
    bonus sample) equal the `_spec_verify_program` reference math
    computed in plain jnp on the same logits/noise."""
    from bpe_transformer_tpu.kernels.pallas.sample import fused_verify_head
    from bpe_transformer_tpu.serving.engine import filter_logits

    rng = np.random.default_rng(4)
    s, k1, d, v = 3, 4, 32, 101
    r = s * k1
    hidden = jnp.asarray(rng.normal(size=(r, d)).astype(np.float32))
    head = jnp.asarray(rng.normal(size=(v, d)).astype(np.float32)) * 0.3
    temps = jnp.repeat(jnp.asarray([0.0, 1.0, 0.8], jnp.float32), k1)
    ks = jnp.repeat(jnp.asarray([0, 7, 0], jnp.int32), k1)
    ps = jnp.repeat(jnp.asarray([2.0, 0.8, 2.0], jnp.float32), k1)
    judge = jnp.asarray(rng.integers(0, v, size=(r,)), jnp.int32)
    q = jax.nn.softmax(
        jnp.asarray(rng.normal(size=(r, v)).astype(np.float32)), axis=-1
    )
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(r))
    gumbel = jax.vmap(lambda k: jax.random.gumbel(k, (v,), jnp.float32))(keys)

    greedy, p_d, bonus = fused_verify_head(
        hidden, head, temps, ks, ps, judge, q, gumbel
    )
    logits = head_logits(hidden, head)
    g_ref = jnp.argmax(logits, axis=-1)
    p_soft = jax.nn.softmax(filter_logits(logits, temps, ks, ps), axis=-1)
    p = jnp.where(
        (temps > 0)[:, None], p_soft, jax.nn.one_hot(g_ref, v)
    )
    pd_ref = jnp.take_along_axis(p, judge[:, None], axis=-1)[:, 0]
    res = jnp.maximum(p - q, 0.0)
    res = jnp.where(jnp.sum(res, -1, keepdims=True) > 0, res, p)
    logres = jnp.where(res > 0, jnp.log(res), -jnp.inf)
    bonus_ref = jnp.where(
        temps > 0,
        jnp.argmax(logres + gumbel, axis=-1),
        jnp.argmax(res, axis=-1),
    )
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(g_ref))
    np.testing.assert_allclose(
        np.asarray(p_d), np.asarray(pd_ref), rtol=0, atol=2e-6
    )
    np.testing.assert_array_equal(np.asarray(bonus), np.asarray(bonus_ref))


# ------------------------------------------------------- engine-level pins


#: The flagship combination (paged + int8) stays tier-1; the other
#: engine/width combinations run in the full matrix (870s-budget
#: discipline, PR 9 precedent — tier-1 keeps one end-to-end pin per
#: claim, the sweep stays behind `slow`).
@pytest.mark.parametrize(
    "weight_dtype",
    [pytest.param(None, marks=pytest.mark.slow), "int8"],
)
@pytest.mark.parametrize(
    "paged",
    [pytest.param(False, marks=pytest.mark.slow), True],
)
def test_engine_greedy_fused_identical_to_unfused(params, paged, weight_dtype):
    """ACCEPTANCE: greedy decode with fused sampling is token-identical
    to the unfused path — on both engines, at both weight widths."""
    from bpe_transformer_tpu.serving.engine import SlotPoolEngine
    from bpe_transformer_tpu.serving.kvpool.paged_engine import PagedEngine

    def build(fused):
        if paged:
            return PagedEngine(
                params, CFG, slots=3, block_size=8,
                weight_dtype=weight_dtype, fused_sampling=fused,
            )
        return SlotPoolEngine(
            params, CFG, slots=3, weight_dtype=weight_dtype,
            fused_sampling=fused,
        )

    prompts = [[1, 2, 3, 4, 5], [9, 8, 7], [11] * 12]
    assert _drain(build(False), prompts) == _drain(build(True), prompts)


@pytest.mark.slow
def test_engine_sampled_fused_matches_unfused_on_cpu(params):
    """On CPU the kernel's logits match the XLA matmul bitwise, so even
    the SAMPLED path is token-identical fused-vs-unfused (the stronger
    form of distribution preservation; on hardware only greedy is
    pinned)."""
    from bpe_transformer_tpu.serving.kvpool.paged_engine import PagedEngine

    prompts = [[1, 2, 3], [7], [5, 5, 5, 5]]
    a = _drain(
        PagedEngine(params, CFG, slots=3, block_size=8),
        prompts, temp=0.9, top_k=9, top_p=0.85,
    )
    b = _drain(
        PagedEngine(params, CFG, slots=3, block_size=8,
                    fused_sampling=True),
        prompts, temp=0.9, top_k=9, top_p=0.85,
    )
    assert a == b


@pytest.mark.slow
def test_quantized_greedy_long_decode_smoke(params):
    """QUALITY GATE: a long greedy decode on int8 weights emits valid
    tokens end to end and tracks the f32 path closely (the per-step
    logit error bound keeps argmax flips rare at this scale)."""
    from bpe_transformer_tpu.serving.kvpool.paged_engine import PagedEngine

    prompts = [[3, 1, 4, 1, 5]]
    ref = _drain(
        PagedEngine(params, CFG, slots=1, block_size=8),
        prompts, max_new_tokens=48,
    )
    got = _drain(
        PagedEngine(params, CFG, slots=1, block_size=8,
                    weight_dtype="int8", fused_sampling=True),
        prompts, max_new_tokens=48,
    )
    (ref_toks,), (got_toks,) = ref.values(), got.values()
    assert len(got_toks) == 48
    assert all(0 <= t < CFG.vocab_size for t in got_toks)
    agree = sum(a == b for a, b in zip(ref_toks, got_toks)) / 48
    assert agree >= 0.8, f"int8 greedy drifted: {agree:.0%} agreement"


@pytest.mark.slow
def test_bounded_compile_quantized_fused_ladder(params):
    """QUALITY GATE: the quantized+fused ladder adds no unbounded
    programs — still one chunk program per bucket + one tick."""
    from bpe_transformer_tpu.serving.kvpool.paged_engine import PagedEngine

    engine = PagedEngine(
        params, CFG, slots=3, block_size=8, weight_dtype="int8",
        fused_sampling=True, prefill_buckets=(8, 16),
    )
    _drain(engine, [[1] * 5, [2] * 12, [3] * 3], max_new_tokens=6)
    _drain(engine, [[4] * 9, [5] * 2], max_new_tokens=6)
    assert engine.compiled_programs() <= len(engine.buckets) + 1


@pytest.mark.slow
def test_spec_greedy_parity_on_quantized_fused_path(params):
    """ACCEPTANCE: the spec-decode greedy parity suite's core pin holds
    on the quantized path — SpecEngine with int8 weights + fused verify
    emits exactly the non-speculative quantized engine's greedy tokens
    (the truncated draft shares the quantized tree, zero extra bytes)."""
    from bpe_transformer_tpu.serving.kvpool.paged_engine import PagedEngine
    from bpe_transformer_tpu.serving.spec.draft import DraftSpec
    from bpe_transformer_tpu.serving.spec.engine import SpecEngine

    prompts = [[1, 2, 3, 4, 5], [9, 8, 7], [11] * 12]
    base = _drain(
        PagedEngine(params, CFG, slots=3, block_size=8,
                    weight_dtype="int8"),
        prompts, max_new_tokens=10,
    )
    spec = SpecEngine(
        params, CFG, draft=DraftSpec(truncate_layers=1), speculate_k=3,
        slots=3, block_size=8, weight_dtype="int8", fused_sampling=True,
    )
    assert _drain(spec, prompts, max_new_tokens=10) == base
    assert spec.draft.param_bytes == 0  # still a zero-copy quantized view
    # Sampled smoke on the same engine: runs, valid tokens, gauges move.
    out = _drain(spec, prompts, temp=0.9, top_k=20, max_new_tokens=6)
    assert all(0 <= t < CFG.vocab_size for ts in out.values() for t in ts)
    assert spec.spec_target_steps > 0


# --------------------------------------------------- serving-layer gauges


def test_serving_stats_statusz_metrics_and_roofline(params):
    """Telemetry satellites: params_bytes / weight_dtype / tick bytes on
    stats() + /statusz + /metrics, and the analytic decode-tick roofline
    wired end to end with the int8 ratio visible."""
    from bpe_transformer_tpu.serving.server import ServingEngine

    act = ServingEngine(params, CFG, slots=2, paged=True, block_size=8)
    q = ServingEngine(
        params, CFG, slots=2, paged=True, block_size=8,
        weight_dtype="int8", fused_sampling=True,
    )
    try:
        sa, sq = act.stats(), q.stats()
        assert sa["weight_dtype"] == "float32" and sq["weight_dtype"] == "int8"
        # ACCEPTANCE: ~2x+ lower weight bytes per tick (4x vs f32 minus
        # scale overhead), pinned via tree bytes like PR 9's pool pin.
        ratio = sq["tick_weight_bytes"] / sa["tick_weight_bytes"]
        assert ratio < 0.45, ratio
        assert sq["params_bytes"] < sa["params_bytes"]
        assert sq["fused_sampling"] is True
        roof = sq["decode_roofline"]
        assert roof["weight_bytes"] == sq["tick_weight_bytes"]
        assert roof["weight_dtype"] == "int8"
        assert roof["kv_bytes"] == 0  # no active slots yet
        zz = q.statusz()
        assert zz["weight_dtype"] == "int8"
        assert zz["decode_roofline"]["fused_sampling"] is True
        prom = q.prometheus_metrics()
        for needle in (
            'bpe_tpu_params_bytes{weight_dtype="int8"}',
            "bpe_tpu_decode_tick_weight_bytes",
            "bpe_tpu_decode_tick_kv_bytes",
        ):
            assert needle in prom, needle
    finally:
        act.close()
        q.close()


def test_roofline_records_emitted_and_schema_valid(params):
    """The kind="roofline" record rides the engine cadence and validates
    against the registered schema (check #5's fixture pins the wire
    format; this pins the live emitter)."""
    from bpe_transformer_tpu.serving.server import ServingEngine
    from bpe_transformer_tpu.telemetry import Telemetry
    from bpe_transformer_tpu.telemetry.schema import validate_record

    records = []
    tel = Telemetry(sink=records.append)
    s = ServingEngine(
        params, CFG, slots=2, paged=True, block_size=8,
        weight_dtype="int8", telemetry=tel, engine_record_every_s=0.0,
    )
    with s:
        s.generate([1, 2, 3], max_new_tokens=6, temperature=0.0,
                   timeout=120)
    roofs = [r for r in records if r.get("kind") == "roofline"]
    assert roofs, [r.get("kind") for r in records]
    for rec in roofs:
        assert not validate_record(rec)
    assert roofs[0]["weight_dtype"] == "int8"
    assert roofs[0]["weight_bytes"] == s.engine.tick_weight_bytes


def test_decode_tick_roofline_math():
    from bpe_transformer_tpu.telemetry.attribution import decode_tick_roofline
    from bpe_transformer_tpu.utils.flops import (
        decode_tick_flops,
        matmul_param_count,
    )

    flops = decode_tick_flops(CFG, 4, 100)
    assert flops == 2.0 * matmul_param_count(CFG) * 4 + (
        4.0 * CFG.num_layers * CFG.d_model * 100
    )
    row = decode_tick_roofline(
        flops=flops, weight_bytes=1000, kv_bytes=500, act_bytes=100,
        device_kind="TPU v5e",
    )
    assert row["bytes_accessed"] == 1600
    assert row["weight_frac"] == 0.625
    assert row["bound"] == "memory-bound"  # AI ~124 under the ~241 ridge
    assert row["projected_tick_s"] is not None
    tiny = decode_tick_roofline(
        flops=flops, weight_bytes=100, kv_bytes=50, act_bytes=10,
        device_kind="TPU v5e",
    )
    assert tiny["bound"] == "compute-bound"  # tiny bytes, big flops
    cpu = decode_tick_roofline(
        flops=flops, weight_bytes=1000, kv_bytes=0, act_bytes=0,
        device_kind="cpu",
    )
    assert cpu["bound"] == "unknown" and cpu["projected_tick_s"] is None


def test_roofline_fixture_pins_report_and_compare_gate():
    """tests/fixtures/roofline_tiny.jsonl is the pinned wire format:
    the report section and the serve_weight_bytes compare-gate row must
    keep reading it."""
    from bpe_transformer_tpu.telemetry.report import (
        compare_metrics,
        extract_compare_metrics,
        render_report,
        summarize,
    )

    records = [
        json.loads(ln)
        for ln in (REPO / "tests/fixtures/roofline_tiny.jsonl")
        .read_text().splitlines()
    ]
    summary = summarize(records)
    assert summary["roofline"]["weight_bytes"] == 13159424
    assert summary["roofline"]["weight_dtype"] == "int8"
    assert summary["roofline"]["bound"] == "memory-bound"
    report = render_report(records)
    assert "== decode roofline (2 samples) ==" in report
    assert "tick weights 13159424 B (int8)" in report

    metrics = extract_compare_metrics(summary)
    assert metrics["serve_weight_bytes"] == (13159424.0, "lower")
    # Weight bytes growing back against an int8 baseline is a gated
    # regression (the quantization win lost).
    bloated = dict(metrics)
    bloated["serve_weight_bytes"] = (26318848.0, "lower")
    _, regressions = compare_metrics(metrics, bloated)
    assert "serve_weight_bytes" in regressions
    _, regressions = compare_metrics(metrics, metrics)
    assert not regressions


@pytest.mark.slow
def test_cli_weight_dtype_rc2_validation(tmp_path):
    """rc-2 validation (PR 9 pattern): --weight-dtype int8 on an MoE
    config is a configuration error the CLI refuses up front — the
    per-channel quantizer does not cover expert stacks."""
    import os
    import subprocess
    import sys as _sys

    moe_cfg = tmp_path / "moe.json"
    moe_cfg.write_text(json.dumps({
        "vocab_size": 64, "context_length": 16, "d_model": 16,
        "num_layers": 1, "num_heads": 2, "d_ff": 32,
        "ffn_type": "moe", "n_experts": 2,
    }))
    proc = subprocess.run(
        [
            _sys.executable, "-m", "bpe_transformer_tpu.training.cli",
            "warmup", "--compile-cache", str(tmp_path / "cc"),
            "--model-config", str(moe_cfg), "--paged",
            "--weight-dtype", "int8",
        ],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": str(REPO)},
        cwd=str(REPO),
    )
    assert proc.returncode == 2
    assert "MoE" in proc.stderr


# ----------------------------------------------------------- tooling guard


def test_tier1_budget_tool_log_mode(tmp_path):
    """The tier-1 budget guard (tools/check_tier1_budget.py) passes a
    within-budget pytest log, fails an over-budget one, and fails loudly
    on a log with no summary trailer (an interrupted/killed run must not
    read as green)."""
    import sys
    sys.path.insert(0, str(REPO / "tools"))
    try:
        import check_tier1_budget as tool
    finally:
        sys.path.pop(0)

    ok = tmp_path / "ok.log"
    ok.write_text("...\n== 398 passed, 27 deselected in 612.34s ==\n")
    assert tool.main([str(ok)]) == 0
    over = tmp_path / "over.log"
    over.write_text("== 430 passed in 845.10s ==\n")
    assert tool.main([str(over)]) == 1
    assert tool.main([str(over), "--budget", "900"]) == 0
    truncated = tmp_path / "killed.log"
    truncated.write_text("...F....\n")  # killed mid-run: no trailer
    assert tool.main([str(truncated)]) == 1
