"""Training-MFU push (ISSUE 13): graduated remat policies, scan-over-layers,
bf16 gradient collectives, and the peak-HBM/step-time gate.

Pins the four acceptance claims on the virtual 8-device CPU mesh:

* grad PARITY — every remat policy (and the scanned layer stack) computes
  the same loss/gradients as the plain forward, across plain/dp/gspmd/
  zero1 and accum/scanned step variants (tiny geometry here; the heavy
  geometry + flash-attention matrix runs behind ``slow``);
* ORDERING — ``save_attn`` compiles to strictly lower peak HBM than
  ``none`` and strictly lower recompute flops than ``full``
  (``memory_analysis``/``cost_analysis`` of the AOT-compiled update);
* bf16 COLLECTIVES — ``grads_dtype="bfloat16"`` halves the bytes the dp
  all-reduce / ZeRO-1 reduce-scatter moves (asserted on the LOWERED
  StableHLO: XLA:CPU's float-normalization pass re-widens bf16 compute
  post-optimization, so the optimized HLO can't pin what a TPU moves),
  with the update staying inside the pinned parity bound;
* MEASUREMENT — the attribution record carries ``train_peak_hbm_bytes`` +
  the remat/precision/scan labels, every step variant still donates its
  buffers, and the report/monitor/compare-gate surfaces render and gate
  the new fields (fixture-pinned).
"""

import dataclasses
import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bpe_transformer_tpu.models import init_params
from bpe_transformer_tpu.models.config import ModelConfig
from bpe_transformer_tpu.optim import adamw_init, sharded_adamw_init
from bpe_transformer_tpu.parallel import (
    make_dp_train_step,
    make_gspmd_train_step,
    make_mesh,
    shard_batch,
)
from bpe_transformer_tpu.training.train_step import (
    TrainHParams,
    make_grad_accum_train_step,
    make_loss_fn,
    make_scanned_train_step,
    make_train_step,
)

CFG = ModelConfig(
    vocab_size=128,
    context_length=64,
    d_model=32,
    num_layers=2,
    num_heads=4,
    d_ff=128,
)
HP = TrainHParams(warmup_iters=2, cosine_cycle_iters=10)

POLICIES = ("none", "full", "dots_saveable", "save_attn")


def _setup(seed=0, batch=8):
    params = init_params(jax.random.PRNGKey(seed), CFG)
    rng = np.random.default_rng(seed)
    x = rng.integers(0, CFG.vocab_size, size=(batch, CFG.context_length))
    return (
        params,
        jnp.asarray(x, jnp.int32),
        jnp.asarray(np.roll(x, -1, axis=1), jnp.int32),
    )


def _flat(tree) -> np.ndarray:
    return np.concatenate(
        [np.ravel(np.asarray(l)) for l in jax.tree_util.tree_leaves(tree)]
    )


def _copy(tree):
    return jax.tree_util.tree_map(jnp.copy, tree)


# ------------------------------------------------------- config semantics


def test_remat_policy_config_semantics():
    """Validation + back-compat of the graduated knob, and the auto
    loss-chunk resolution for bf16 configs."""
    with pytest.raises(ValueError, match="remat_policy"):
        dataclasses.replace(CFG, remat_policy="selective")
    # The deprecated bool maps to full; contradicting an explicit policy
    # is refused rather than silently resolved.
    assert dataclasses.replace(CFG, remat=True).resolved_remat_policy == "full"
    assert (
        dataclasses.replace(CFG, remat=True, remat_policy="full")
        .resolved_remat_policy
        == "full"
    )
    with pytest.raises(ValueError, match="deprecated alias"):
        dataclasses.replace(CFG, remat=True, remat_policy="save_attn")
    assert CFG.resolved_remat_policy == "none"

    # loss_chunk: None = auto (chunk bf16 configs whose context exceeds
    # the auto chunk — a chunk >= seq shrinks nothing), 0 = force full
    # logits, N = explicit.
    assert CFG.loss_chunk is None
    bf16 = dataclasses.replace(CFG, activation_dtype="bfloat16")
    assert bf16.loss_chunk is None  # context 64 <= AUTO_LOSS_CHUNK
    bf16_long = dataclasses.replace(bf16, context_length=512)
    assert bf16_long.loss_chunk == ModelConfig.AUTO_LOSS_CHUNK
    assert (
        dataclasses.replace(bf16_long, loss_chunk_size=0).loss_chunk is None
    )
    assert dataclasses.replace(CFG, loss_chunk_size=16).loss_chunk == 16
    with pytest.raises(ValueError, match="loss_chunk_size"):
        dataclasses.replace(CFG, loss_chunk_size=-1)
    with pytest.raises(ValueError, match="grads_dtype"):
        TrainHParams(grads_dtype="float16")


# ----------------------------------------------------------- grad parity


def test_remat_policy_grad_parity_tiny():
    """Every policy — and the deprecated remat bool — computes identical
    loss and gradients (remat changes WHEN, never WHAT)."""
    params, x, y = _setup()
    ref_loss = ref_grads = None
    variants = [
        dataclasses.replace(CFG, remat_policy=p) for p in POLICIES
    ] + [dataclasses.replace(CFG, remat=True)]
    for cfg in variants:
        loss, grads = jax.jit(jax.value_and_grad(make_loss_fn(cfg)))(
            params, x, y
        )
        if ref_loss is None:
            ref_loss, ref_grads = float(loss), _flat(grads)
            continue
        np.testing.assert_allclose(float(loss), ref_loss, rtol=1e-6)
        np.testing.assert_allclose(
            _flat(grads), ref_grads, rtol=2e-5, atol=1e-6
        )


def test_scan_layers_parity_including_stats():
    """The scanned layer stack is numerically the unrolled one — forward,
    gradients, AND the dynamics activation stats (whose per-layer stacking
    the scan performs itself)."""
    from bpe_transformer_tpu.models.transformer import (
        forward_hidden,
        forward_hidden_stats,
    )

    params, x, y = _setup()
    base_h, _ = jax.jit(
        lambda p, t: forward_hidden(p, t, CFG)
    )(params, x)
    _, grads_ref = jax.jit(jax.value_and_grad(make_loss_fn(CFG)))(params, x, y)
    _, _, stats_ref = jax.jit(
        lambda p, t: forward_hidden_stats(p, t, CFG)
    )(params, x)

    for policy in ("none", "save_attn", "full"):
        cfg = dataclasses.replace(CFG, scan_layers=True, remat_policy=policy)
        h, _ = jax.jit(lambda p, t, c=cfg: forward_hidden(p, t, c))(params, x)
        np.testing.assert_allclose(
            np.asarray(h), np.asarray(base_h), rtol=2e-5, atol=1e-6
        )
        _, grads = jax.jit(jax.value_and_grad(make_loss_fn(cfg)))(params, x, y)
        np.testing.assert_allclose(
            _flat(grads), _flat(grads_ref), rtol=2e-5, atol=1e-6
        )
        _, _, stats = jax.jit(
            lambda p, t, c=cfg: forward_hidden_stats(p, t, c)
        )(params, x)
        assert stats["rms"].shape == (CFG.num_layers,)
        for key in stats_ref:
            np.testing.assert_allclose(
                np.asarray(stats[key]), np.asarray(stats_ref[key]),
                rtol=2e-5, atol=1e-6,
            )


@pytest.mark.slow
def test_remat_policy_parity_matrix_heavy():
    """Heavy-geometry parity matrix: policies x {dp, gspmd, zero1} x
    {plain, accum, scanned} against the single-device none reference —
    one optimizer step each, params compared."""
    cfg0 = dataclasses.replace(CFG, context_length=128, d_model=64, d_ff=256)
    params = init_params(jax.random.PRNGKey(0), cfg0)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg0.vocab_size, size=(16, cfg0.context_length))
    x = jnp.asarray(ids, jnp.int32)
    y = jnp.asarray(np.roll(ids, -1, axis=1), jnp.int32)
    mesh = make_mesh({"data": 8})
    xb, yb = shard_batch((x, y), mesh)
    xs = x.reshape(2, 8, -1)
    ys = y.reshape(2, 8, -1)
    xsb, ysb = shard_batch((xs, ys), mesh, stacked=True)

    ref = None
    for policy in POLICIES:
        cfg = dataclasses.replace(cfg0, remat_policy=policy)
        step = make_train_step(cfg, HP)
        p_ref, _, _ = step(_copy(params), adamw_init(params), x, y)
        if ref is None:
            ref = _flat(p_ref)
        else:
            np.testing.assert_allclose(_flat(p_ref), ref, atol=2e-5)

        dp = make_dp_train_step(cfg, HP, mesh)
        p_dp, _, _ = dp(_copy(params), adamw_init(params), xb, yb)
        np.testing.assert_allclose(_flat(p_dp), ref, atol=2e-5)

        gs = make_gspmd_train_step(cfg, HP, mesh, "dp", example_params=params)
        p_gs, _, _ = gs(_copy(params), adamw_init(params), xb, yb)
        np.testing.assert_allclose(_flat(p_gs), ref, atol=2e-5)

        z = make_dp_train_step(cfg, HP, mesh, opt_sharding="zero1")
        p_z, _, _ = z(
            _copy(params), sharded_adamw_init(params, 8, mesh=mesh), xb, yb
        )
        np.testing.assert_allclose(_flat(p_z), ref, atol=2e-5)

        acc = make_dp_train_step(cfg, HP, mesh, accum_steps=2)
        p_a, _, _ = acc(_copy(params), adamw_init(params), xsb, ysb)
        # accum averages microbatch means — same numerics, different
        # reduction order.
        np.testing.assert_allclose(_flat(p_a), ref, atol=5e-5)

    # scanned variant (2 inner updates) only needs self-consistency across
    # policies: none vs save_attn.
    xs2, ys2 = shard_batch(
        (jnp.stack([x, y]), jnp.stack([y, x])), mesh, stacked=True
    )
    scanned_ref = None
    for policy in ("none", "save_attn"):
        cfg = dataclasses.replace(cfg0, remat_policy=policy)
        sc = make_dp_train_step(cfg, HP, mesh, inner_steps=2)
        p_s, _, _ = sc(_copy(params), adamw_init(params), xs2, ys2)
        if scanned_ref is None:
            scanned_ref = _flat(p_s)
        else:
            np.testing.assert_allclose(_flat(p_s), scanned_ref, atol=2e-5)


@pytest.mark.slow
def test_remat_policy_flash_attention_parity_and_ordering():
    """The FA-2 residual-reuse claim on the flash kernel itself: with
    attention_impl="flash" every policy stays grad-exact, and the
    compiled-update counters order as the policy ladder promises —
    save_attn strictly below none on peak HBM and strictly below full on
    flops (full re-runs the kernel; save_attn keeps its residuals)."""
    cfg0 = dataclasses.replace(
        CFG, context_length=256, d_model=64, d_ff=256,
        attention_impl="flash", flash_block_size=128,
    )
    params = init_params(jax.random.PRNGKey(0), cfg0)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg0.vocab_size, size=(8, 256))
    x = jnp.asarray(ids, jnp.int32)
    y = jnp.asarray(np.roll(ids, -1, axis=1), jnp.int32)

    ref = None
    rows = {}
    for policy in POLICIES:
        cfg = dataclasses.replace(cfg0, remat_policy=policy)
        grad_fn = jax.jit(jax.value_and_grad(make_loss_fn(cfg)))
        loss, grads = grad_fn(params, x, y)
        if ref is None:
            ref = (float(loss), _flat(grads))
        else:
            np.testing.assert_allclose(float(loss), ref[0], rtol=1e-6)
            np.testing.assert_allclose(
                _flat(grads), ref[1], rtol=2e-5, atol=1e-6
            )
        compiled = grad_fn.lower(params, x, y).compile()
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0]
        rows[policy] = (
            float(analysis["flops"]),
            int(compiled.memory_analysis().temp_size_in_bytes),
        )
    assert rows["save_attn"][1] < rows["none"][1]
    assert rows["save_attn"][0] < rows["full"][0]
    assert rows["full"][0] > rows["none"][0]
    assert rows["full"][1] <= rows["save_attn"][1]


# ------------------------------------------------- memory/flops ordering


def test_remat_policy_memory_flops_ordering():
    """The acceptance ordering on the AOT-compiled update (tiny geometry,
    XLA attention — the flash variant runs behind slow): save_attn's peak
    HBM strictly below none's, its recompute flops strictly below full's,
    and full strictly above none on flops (it recomputes everything)."""
    cfg0 = dataclasses.replace(CFG, context_length=128, d_ff=256)
    params = init_params(jax.random.PRNGKey(0), cfg0)
    x = jnp.zeros((8, 128), jnp.int32)

    rows = {}
    for policy in POLICIES:
        cfg = dataclasses.replace(cfg0, remat_policy=policy)
        compiled = (
            jax.jit(jax.grad(make_loss_fn(cfg)))
            .lower(params, x, x)
            .compile()
        )
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0]
        memory = compiled.memory_analysis()
        assert memory is not None and memory.temp_size_in_bytes > 0
        rows[policy] = (
            float(analysis["flops"]), int(memory.temp_size_in_bytes)
        )

    flops = {p: rows[p][0] for p in rows}
    temp = {p: rows[p][1] for p in rows}
    assert temp["save_attn"] < temp["none"], rows
    assert flops["save_attn"] < flops["full"], rows
    assert flops["full"] > flops["none"], rows
    assert temp["full"] <= temp["save_attn"], rows


def test_chunked_ce_default_drops_full_logits_buffer():
    """bf16 configs chunk the LM loss by default: the compiled step's HLO
    never materializes the f32 (B, T, V) logits tensor (the peak-memory
    spike the remat policy fights), while an explicit loss_chunk_size=0
    provably does — and both compute the same loss."""
    # vocab deliberately distinct from every other config dim (d_ff etc.)
    # so the (B, T, V) shape probe below cannot collide with an FFN or
    # attention buffer that merely shares the byte shape.
    bf16 = dataclasses.replace(
        CFG, activation_dtype="bfloat16", context_length=512, vocab_size=160
    )
    full = dataclasses.replace(bf16, loss_chunk_size=0)
    params = init_params(jax.random.PRNGKey(0), bf16)
    batch = 2
    rng = np.random.default_rng(1)
    ids = rng.integers(0, bf16.vocab_size, size=(batch, bf16.context_length))
    x = jnp.asarray(ids, jnp.int32)
    y = jnp.asarray(np.roll(ids, -1, axis=1), jnp.int32)

    logits_shape = f"f32[{batch},{bf16.context_length},{bf16.vocab_size}]"

    def step_hlo(cfg):
        step = make_train_step(cfg, HP)
        return step.lower(
            params, adamw_init(params), x, y
        ).compile().as_text()

    assert logits_shape in step_hlo(full)
    assert logits_shape not in step_hlo(bf16)

    loss_auto = float(jax.jit(make_loss_fn(bf16))(params, x, y))
    loss_full = float(jax.jit(make_loss_fn(full))(params, x, y))
    np.testing.assert_allclose(loss_auto, loss_full, rtol=1e-5)


# --------------------------------------------------- bf16 grad collectives


def _lowered_reduce_bytes(lowered_text: str, op: str) -> int:
    """Sum the operand bytes of every ``stablehlo.<op>`` in lowered IR."""
    total = 0
    pattern = re.compile(
        r"stablehlo\." + op + r".*?\}\)\s*:\s*\(tensor<([0-9x]*)x?(f32|bf16)>\)",
        re.S,
    )
    for dims, dtype in pattern.findall(lowered_text):
        n = 1
        for d in dims.split("x"):
            if d:
                n *= int(d)
        total += n * (4 if dtype == "f32" else 2)
    return total


def test_grads_dtype_bfloat16_halves_collective_bytes():
    """The dp all-reduce and the ZeRO-1 reduce-scatter move HALF the bytes
    under grads_dtype="bfloat16" — pinned on the lowered StableHLO, where
    the requested collective width is still visible (XLA:CPU's
    float-normalization re-widens bf16 post-optimization; a TPU moves the
    narrow bytes as lowered)."""
    mesh = make_mesh({"data": 8})
    params, x, y = _setup()
    xb, yb = shard_batch((x, y), mesh)

    bytes_by = {}
    for gd in ("float32", "bfloat16"):
        hp = dataclasses.replace(HP, grads_dtype=gd)
        step = make_dp_train_step(CFG, hp, mesh)
        text = step.lower(params, adamw_init(params), xb, yb).as_text()
        bytes_by[("dp", gd)] = _lowered_reduce_bytes(text, "all_reduce")

        zstep = make_dp_train_step(CFG, hp, mesh, opt_sharding="zero1")
        opt = sharded_adamw_init(params, 8, mesh=mesh)
        ztext = zstep.lower(params, opt, xb, yb).as_text()
        bytes_by[("zero1", gd)] = _lowered_reduce_bytes(
            ztext, "reduce_scatter"
        )

    for mode in ("dp", "zero1"):
        f32 = bytes_by[(mode, "float32")]
        bf16 = bytes_by[(mode, "bfloat16")]
        assert f32 > 0
        # The grad tree halves exactly; the dp variant keeps a few f32
        # scalar reductions (loss), hence <= 0.55 rather than == 0.5.
        assert bf16 <= 0.55 * f32, (mode, f32, bf16)


def test_grads_dtype_parity_bound():
    """Two optimizer steps with bf16 gradient collectives stay inside the
    pinned parity bound of the f32 path — dp and ZeRO-1 (whose bf16
    reduce-scatter must agree with dp's bf16 pmean), single-device pays
    the same rounding by construction."""
    mesh = make_mesh({"data": 8})
    params, x, y = _setup()
    xb, yb = shard_batch((x, y), mesh)
    params2, x2, y2 = _setup(seed=1)
    x2b, y2b = shard_batch((x2, y2), mesh)

    def run(step, opt):
        p, s = _copy(params), opt
        p, s, _ = step(p, s, xb, yb)
        p, s, m = step(p, s, x2b, y2b)
        return _flat(p), float(m["loss"])

    ref_p, ref_loss = run(
        make_dp_train_step(CFG, HP, mesh), adamw_init(params)
    )
    hp16 = dataclasses.replace(HP, grads_dtype="bfloat16")
    p16, loss16 = run(
        make_dp_train_step(CFG, hp16, mesh), adamw_init(params)
    )
    # bf16 rounds ~8 mantissa bits off each gradient; after two AdamW
    # steps the parameter drift stays well under the update scale.
    assert np.max(np.abs(p16 - ref_p)) < 5e-4
    assert abs(loss16 - ref_loss) < 5e-3

    pz16, _ = run(
        make_dp_train_step(CFG, hp16, mesh, opt_sharding="zero1"),
        sharded_adamw_init(params, 8, mesh=mesh),
    )
    # Same narrow collective width; only the reduction ORDER differs
    # (psum vs psum_scatter), so zero1 tracks dp tightly.
    assert np.max(np.abs(pz16 - p16)) < 5e-4

    # Single device pays the identical bf16 round-trip: its drift from
    # the f32 single-device path obeys the same bound as dp's.
    def run_single(hp):
        p, s = _copy(params), adamw_init(params)
        p, s, _ = make_train_step(CFG, hp)(p, s, x, y)
        p, s, _ = make_train_step(CFG, hp)(p, s, x2, y2)
        return _flat(p)

    assert (
        np.max(np.abs(run_single(hp16) - run_single(HP))) < 5e-4
    )


# --------------------------------------------- donation + attribution gate


def test_donation_audit_every_step_variant():
    """Every step variant keeps donating params/opt-state under the new
    knobs (the update happens in place in HBM) — plain, grad-accum,
    scanned, dp, and zero1, at save_attn + scan_layers + bf16 grads."""
    cfg = dataclasses.replace(
        CFG, remat_policy="save_attn", scan_layers=True
    )
    hp = dataclasses.replace(HP, grads_dtype="bfloat16")

    def assert_donated(tree):
        assert all(
            leaf.is_deleted() for leaf in jax.tree_util.tree_leaves(tree)
        )

    params, x, y = _setup()
    step = make_train_step(cfg, hp)
    opt = adamw_init(params)
    step(params, opt, x, y)
    assert_donated(params)
    assert_donated(tuple(opt))

    params, x, y = _setup()
    accum = make_grad_accum_train_step(cfg, hp, 2)
    opt = adamw_init(params)
    accum(params, opt, x.reshape(2, 4, -1), y.reshape(2, 4, -1))
    assert_donated(params)
    assert_donated(tuple(opt))

    params, x, y = _setup()
    scanned = make_scanned_train_step(cfg, hp, 2)
    opt = adamw_init(params)
    scanned(params, opt, jnp.stack([x, x]), jnp.stack([y, y]))
    assert_donated(params)
    assert_donated(tuple(opt))

    mesh = make_mesh({"data": 8})
    params, x, y = _setup()
    xb, yb = shard_batch((x, y), mesh)
    dp = make_dp_train_step(cfg, hp, mesh)
    opt = adamw_init(params)
    dp(params, opt, xb, yb)
    assert_donated(params)
    assert_donated(tuple(opt))

    params, x, y = _setup()
    xb, yb = shard_batch((x, y), mesh)
    z = make_dp_train_step(cfg, hp, mesh, opt_sharding="zero1")
    opt = sharded_adamw_init(params, 8, mesh=mesh)
    z(params, opt, xb, yb)
    assert_donated(params)
    assert_donated(tuple(opt))


def test_attribution_record_carries_peak_hbm_and_knob_labels():
    """The StepProbe stamps the compiled step's peak-HBM envelope and the
    remat/precision/scan labels onto every attribution record, and its
    memory accounting orders save_attn under none like the direct
    compile-counter test above."""
    from bpe_transformer_tpu.telemetry.attribution import StepProbe
    from bpe_transformer_tpu.telemetry.schema import validate_record

    cfg = dataclasses.replace(
        CFG, remat_policy="save_attn", scan_layers=True
    )
    hp = dataclasses.replace(HP, grads_dtype="bfloat16")
    params, x, y = _setup()
    opt = adamw_init(params)
    probe = StepProbe(cfg, hp, batch_size=8, iters=1)
    record = probe.attribution_record(
        params, opt, step=1, wall_step_s=0.01, t=0.0
    )
    assert validate_record(record) == []
    assert record["remat_policy"] == "save_attn"
    assert record["grads_dtype"] == "bfloat16"
    assert record["scan_layers"] is True
    assert record["train_peak_hbm_bytes"] > 0
    assert record["train_temp_hbm_bytes"] > 0
    assert (
        record["train_temp_hbm_bytes"] < record["train_peak_hbm_bytes"]
    )

    # Cross-policy: the probe's peak for save_attn sits under none's.
    probe_none = StepProbe(CFG, HP, batch_size=8, iters=1)
    mem_none = probe_none.memory_stats(params, opt)
    mem_attn = probe.memory_stats(params, opt)
    assert mem_attn["temp_bytes"] < mem_none["temp_bytes"]


def test_report_monitor_compare_gate_peak_hbm(tmp_path, capsys):
    """The fixture-pinned surfaces: report renders the peak-HBM line with
    its knob labels, the compare gate trips on a grown
    train_peak_hbm_bytes (lower-is-better) and on a sunk
    mfu_compute_ceiling, and monitor folds the new fields."""
    from pathlib import Path

    from bpe_transformer_tpu.telemetry.monitor import (
        fold_records,
        render_frame,
    )
    from bpe_transformer_tpu.telemetry.report import (
        load_records,
        main as report_main,
    )

    fixtures = Path(__file__).parent / "fixtures"
    fixture = str(fixtures / "attribution_tiny.jsonl")
    assert report_main([fixture]) == 0
    out = capsys.readouterr().out
    assert "train step peak HBM 8,704.0 MiB" in out
    assert "remat=save_attn" in out and "grads=bfloat16" in out
    assert "scan_layers" in out

    # Self-compare carries the new gate rows.
    assert report_main([fixture, "--compare", fixture]) == 0
    out = capsys.readouterr().out
    assert "train_peak_hbm_bytes" in out
    assert "mfu_compute_ceiling" in out

    # A stream whose compiled-step peak grew 30%: exit 3, row named.
    regressed = tmp_path / "peak_regressed.jsonl"
    regressed.write_text(
        Path(fixture).read_text().replace(
            '"train_peak_hbm_bytes": 9126805504',
            '"train_peak_hbm_bytes": 12126805504',
        )
    )
    assert report_main([str(regressed), "--compare", fixture]) == 3
    assert "train_peak_hbm_bytes" in capsys.readouterr().out

    # --baseline against a bench-capture JSON pinning the peak: the same
    # row gates alongside the existing throughput rows.
    import json as json_mod

    baseline = tmp_path / "baseline.json"
    baseline.write_text(json_mod.dumps({"parsed": {
        "value": 674286.8,
        "mfu": 0.128,
        "train_peak_hbm_bytes": 9126805504,
    }}))
    assert report_main([fixture, "--baseline", str(baseline)]) == 0
    capsys.readouterr()
    assert report_main([str(regressed), "--baseline", str(baseline)]) == 3
    assert "train_peak_hbm_bytes" in capsys.readouterr().out

    state = fold_records(load_records(Path(fixture)))
    assert state["train_peak_hbm_bytes"] == 9126805504
    assert state["remat_policy"] == "save_attn"
    frame = render_frame(state, "fixture")
    assert "remat save_attn" in frame
    assert "grads bfloat16" in frame
    assert "scan_layers" in frame


# ------------------------------------------------------------ CLI wiring


def test_cli_mfu_knob_wiring(capsys):
    """--remat-policy/--scan-layers fold into the model config (explicit
    flag silences and overrides the deprecated bool, which otherwise earns
    a deprecation note), and the flags exist on train/warmup/profile."""
    import argparse

    from bpe_transformer_tpu.training.cli import (
        _apply_mfu_knobs,
        build_parser,
    )

    args = argparse.Namespace(
        remat_policy="save_attn", scan_layers=True, grads_dtype="bfloat16"
    )
    old = dataclasses.replace(CFG, remat=True)
    cfg = _apply_mfu_knobs(old, args)
    assert cfg.resolved_remat_policy == "save_attn"
    assert cfg.scan_layers is True
    assert cfg.remat is False
    assert capsys.readouterr().err == ""  # explicit flag: no note

    none_args = argparse.Namespace(
        remat_policy=None, scan_layers=False, grads_dtype="float32"
    )
    cfg = _apply_mfu_knobs(old, none_args)
    assert cfg.resolved_remat_policy == "full"  # back-compat honored
    assert "deprecated" in capsys.readouterr().err

    parser = build_parser()
    for argv in (
        ["train", "--data", "d.bin", "--remat-policy", "save_attn",
         "--scan-layers", "--grads-dtype", "bfloat16"],
        ["warmup", "--compile-cache", "c", "--train",
         "--remat-policy", "dots_saveable", "--grads-dtype", "bfloat16"],
        ["profile", "--remat-policy", "full", "--scan-layers"],
    ):
        parsed = parser.parse_args(argv)
        assert parsed.grads_dtype in ("float32", "bfloat16")
    with pytest.raises(SystemExit):
        parser.parse_args(["train", "--data", "d.bin",
                           "--remat-policy", "everything"])
    with pytest.raises(SystemExit):
        parser.parse_args(["train", "--data", "d.bin",
                           "--grads-dtype", "fp8"])
