"""Unified telemetry subsystem: spans, watchdog, manifests, health stats,
and the `bpe-tpu report` summarizer — all CPU-testable.

The fast tier-1 anchor for the observability layer: everything here runs in
seconds under JAX_PLATFORMS=cpu (the integration tests train a byte-level
2-layer model for a handful of steps).
"""

import json
import math
from pathlib import Path

import jax.numpy as jnp
import numpy as np
import pytest

from bpe_transformer_tpu.models import ModelConfig
from bpe_transformer_tpu.telemetry import (
    NonFiniteError,
    Telemetry,
    Watchdog,
    flatten_health,
    git_sha,
    group_norms,
    health_metrics,
    nonfinite_count,
    nonfinite_fields,
    run_manifest,
)
from bpe_transformer_tpu.telemetry.health import group_of
from bpe_transformer_tpu.telemetry.report import (
    load_records,
    render_report,
    summarize,
)

TINY = ModelConfig(
    vocab_size=128,
    context_length=16,
    d_model=32,
    num_layers=2,
    num_heads=2,
    d_ff=64,
)


# --------------------------------------------------------------- span/event


def test_spans_nest_and_emit_structured_records():
    records = []
    t = Telemetry(sink=records.append)
    with t.span("setup"):
        with t.span("resume", path_hint="x"):
            pass
        t.event("checkpoint_loaded", step=5)
    kinds = [r["kind"] for r in records]
    assert kinds == ["span", "event", "span"]  # inner span closes first
    inner, event, outer = records
    assert inner["path"] == "setup/resume" and inner["name"] == "resume"
    assert inner["path_hint"] == "x"  # attrs pass through
    assert outer["path"] == "setup"
    assert outer["dur_s"] >= inner["dur_s"] >= 0
    assert event["name"] == "checkpoint_loaded" and event["step"] == 5
    assert event["t"] >= 0


def test_span_handle_end_is_idempotent_and_returns_duration():
    records = []
    t = Telemetry(sink=records.append)
    handle = t.start_span("compile")
    dur = handle.end(cache_hit=False)
    assert dur >= 0
    assert handle.end() == 0.0  # second close: no duplicate record
    assert len(records) == 1
    assert records[0]["cache_hit"] is False


def test_buffering_flushes_on_attach_and_bare_telemetry_is_noop():
    t = Telemetry()  # no sink: records buffer
    t.event("early", n=1)
    with t.span("setup"):
        pass
    records = []
    t.attach(records.append)
    assert [r["name"] for r in records] == ["early", "setup"]
    t.event("late")  # post-attach records flow straight through
    assert records[-1]["name"] == "late"
    Telemetry().event("dropped")  # never attached: silently dropped


def test_footer_reports_record_counts():
    records = []
    t = Telemetry(sink=records.append)
    t.event("nonfinite")
    t.event("nonfinite")
    t.footer(steps=100, clean=True)
    footer = records[-1]
    assert footer["kind"] == "footer"
    assert footer["clean"] is True and footer["steps"] == 100
    assert footer["record_counts"]["event:nonfinite"] == 2


# ----------------------------------------------------------------- watchdog


def _fake_clock(now):
    return lambda: now[0]


def test_watchdog_flags_hang_once_per_gap_and_rearms_on_beat():
    now = [0.0]
    records = []
    hangs = []
    wd = Watchdog(
        factor=4.0,
        min_history=3,
        min_timeout_s=0.0,
        telemetry=Telemetry(sink=records.append),
        on_hang=hangs.append,
        clock=_fake_clock(now),
    )
    assert wd.check() is False  # no history yet: cannot judge
    for _ in range(3):
        wd.beat(1.0)
    assert wd.hang_timeout_s() == pytest.approx(4.0)
    now[0] = 3.0
    assert wd.check() is False  # within deadline
    now[0] = 10.0
    assert wd.check() is True
    assert wd.check() is False  # once per silent gap
    assert wd.hang_events == 1
    assert hangs and hangs[0] == pytest.approx(10.0)
    event = records[-1]
    assert event["name"] == "watchdog_hang"
    assert event["silent_s"] == pytest.approx(10.0)
    wd.beat(1.0)  # new beat re-arms detection
    now[0] = 30.0
    assert wd.check() is True
    assert wd.hang_events == 2


def test_watchdog_median_resists_one_slow_step_and_floors_timeout():
    now = [0.0]
    wd = Watchdog(factor=2.0, min_history=3, min_timeout_s=5.0, clock=_fake_clock(now))
    for step_s in (0.01, 0.01, 0.01, 100.0):
        wd.beat(step_s)
    # Median 0.01 -> 2x median is 0.02, floored to min_timeout_s.
    assert wd.hang_timeout_s() == pytest.approx(5.0)


def test_watchdog_pause_suspends_detection_and_rearms():
    now = [0.0]
    wd = Watchdog(factor=2.0, min_history=3, min_timeout_s=0.0, clock=_fake_clock(now))
    for _ in range(3):
        wd.beat(1.0)
    with wd.pause():
        now[0] = 100.0  # way past the 2s deadline: legitimate long phase
        assert wd.check() is False
    assert wd.hang_events == 0
    # Exit re-armed the deadline from the pause's end, not the last beat.
    now[0] = 101.0
    assert wd.check() is False
    now[0] = 110.0
    assert wd.check() is True


def test_watchdog_nonfinite_policy_raise_dumps_then_raises():
    records = []
    wd = Watchdog(policy="raise", telemetry=Telemetry(sink=records.append))
    bad = {"step": 7, "loss": float("nan")}
    with pytest.raises(NonFiniteError, match="step 7"):
        wd.on_nonfinite(bad, ["loss"])
    # The evidence reached the stream BEFORE the raise.
    assert records[-1]["name"] == "nonfinite"
    assert records[-1]["record"]["step"] == 7
    assert wd.nonfinite_events == 1


def test_watchdog_nonfinite_policy_skip_records_and_continues():
    records = []
    wd = Watchdog(policy="skip", telemetry=Telemetry(sink=records.append))
    wd.on_nonfinite({"step": 3}, ["grad_norm/attn"])
    assert wd.nonfinite_events == 1
    assert records[-1]["fields"] == ["grad_norm/attn"]


def test_watchdog_rejects_unknown_policy():
    with pytest.raises(ValueError, match="policy"):
        Watchdog(policy="explode")


def test_watchdog_thread_lifecycle():
    wd = Watchdog(poll_interval_s=0.01)
    with wd:
        assert wd._thread is not None
    assert wd._thread is None  # stop() joined it
    wd.stop()  # idempotent


# ----------------------------------------------------------------- manifest


def test_run_manifest_is_json_serializable_and_self_describing():
    m = run_manifest(
        kind="train",
        model_config=TINY,
        loop_config={"steps": 10},
        parallel="dp",
        extra={"n_chips": 8},
    )
    json.dumps(m)  # must round-trip as one JSON record
    assert m["kind"] == "manifest" and m["run_kind"] == "train"
    assert m["model_config"]["d_model"] == 32
    assert m["loop_config"] == {"steps": 10}
    assert m["parallel"] == "dp" and m["n_chips"] == 8
    assert m["jax_version"]  # backend reachable in tests
    assert m["devices"]["platform"] == "cpu"
    assert m["host"] and m["python"]


def test_git_sha_inside_and_outside_a_checkout(tmp_path):
    sha = git_sha()
    assert sha is None or len(sha.split("-")[0]) == 40
    assert git_sha(cwd=tmp_path) is None  # not a checkout: None, no raise


def test_attach_manifest_never_loses_the_payload(monkeypatch):
    from bpe_transformer_tpu.telemetry import manifest as manifest_mod

    payload = manifest_mod.attach_manifest({"tok_s": 1.0}, kind="bench")
    assert payload["manifest"]["run_kind"] == "bench"

    def boom(**kw):
        raise RuntimeError("no backend")

    monkeypatch.setattr(manifest_mod, "run_manifest", boom)
    payload = manifest_mod.attach_manifest({"tok_s": 1.0}, kind="bench")
    assert payload == {"tok_s": 1.0}  # un-annotated, not raised


# ------------------------------------------------------- device-side health


def test_group_of_buckets_canonical_layer_groups():
    assert group_of("['layers'][0]['attn']['wq']") == "attn"
    assert group_of("['layers'][0]['ffn']['w1']") == "ffn"
    assert group_of("['token_embeddings']") == "embed"
    assert group_of("['lm_head']") == "head"
    assert group_of("['layers'][0]['ln1']") == "norm"
    assert group_of("['something_else']") == "other"


def test_group_norms_and_nonfinite_count():
    tree = {
        "attn": {"w": jnp.full((4,), 3.0)},
        "ffn": {"w": jnp.array([4.0, float("inf")])},
    }
    norms = group_norms(tree)
    assert norms["attn"] == pytest.approx(6.0)  # sqrt(4 * 9)
    assert int(nonfinite_count(tree)) == 1
    # bf16 leaves accumulate in f32: no overflow at moderate norms.
    big = {"attn": jnp.full((1024,), 300.0, dtype=jnp.bfloat16)}
    assert math.isfinite(float(group_norms(big)["attn"]))


def test_flatten_health_produces_flat_jsonl_keys():
    health = health_metrics(
        jnp.float32(2.5),
        {"attn": jnp.ones(3)},
        {"attn": jnp.ones(3), "lm_head": jnp.full(2, float("nan"))},
    )
    flat = flatten_health({**health, "moe_aux": jnp.float32(1.25)})
    assert flat["nonfinite_loss"] == 0
    assert flat["nonfinite_params"] == 2
    assert flat["grad_norm/attn"] == pytest.approx(math.sqrt(3.0))
    assert math.isnan(flat["param_norm/head"])
    assert flat["moe_aux"] == pytest.approx(1.25)


def test_health_enabled_train_step_exports_group_norms():
    import jax

    from bpe_transformer_tpu.models import init_params
    from bpe_transformer_tpu.optim import adamw_init
    from bpe_transformer_tpu.training.train_step import (
        TrainHParams,
        make_train_step,
    )

    params = init_params(jax.random.PRNGKey(0), TINY)
    opt_state = adamw_init(params)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, TINY.vocab_size, size=(4, TINY.context_length))
    x, y = jnp.asarray(ids), jnp.asarray(np.roll(ids, -1, axis=1))

    # Default step: metrics unchanged (no health key, no extra cost).
    _, _, metrics = make_train_step(TINY, TrainHParams())(params, opt_state, x, y)
    assert "health" not in metrics

    params = init_params(jax.random.PRNGKey(0), TINY)
    step = make_train_step(TINY, TrainHParams(), health=True)
    _, _, metrics = step(params, adamw_init(params), x, y)
    flat = flatten_health(jax.device_get(metrics["health"]))
    assert flat["nonfinite_loss"] == 0
    assert flat["nonfinite_grads"] == 0 and flat["nonfinite_params"] == 0
    for group in ("attn", "ffn", "embed", "head", "norm"):
        assert flat[f"grad_norm/{group}"] >= 0
        assert flat[f"param_norm/{group}"] > 0


def test_health_enabled_moe_step_exports_expert_balance():
    import dataclasses

    import jax

    from bpe_transformer_tpu.models import init_params
    from bpe_transformer_tpu.optim import adamw_init
    from bpe_transformer_tpu.training.train_step import (
        TrainHParams,
        make_train_step,
    )

    moe = dataclasses.replace(TINY, ffn_type="moe", n_experts=4)
    params = init_params(jax.random.PRNGKey(0), moe)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, moe.vocab_size, size=(4, moe.context_length))
    x, y = jnp.asarray(ids), jnp.asarray(np.roll(ids, -1, axis=1))
    step = make_train_step(moe, TrainHParams(), health=True)
    _, _, metrics = step(params, adamw_init(params), x, y)
    moe_aux = float(metrics["health"]["moe_aux"])
    # Switch-style load-balance loss: 1.0 at uniform routing, and bounded
    # by n_experts (all traffic on one expert).
    assert 0.5 <= moe_aux <= moe.n_experts + 0.5


# ------------------------------------------------------------------- report


def test_nonfinite_fields_flags_counts_and_nonfinite_values():
    assert nonfinite_fields({"loss": 2.0, "grad_norm/attn": 1.0}) == []
    assert nonfinite_fields({"nonfinite_grads": 3}) == ["nonfinite_grads"]
    assert nonfinite_fields({"loss": float("nan")}) == ["loss"]
    # The global grad_norm every run logs is value-checked even without
    # --health-stats: an Inf grad norm must trip the watchdog policy.
    assert nonfinite_fields({"grad_norm": float("inf")}) == ["grad_norm"]
    assert nonfinite_fields({"param_norm/ffn": float("inf")}) == ["param_norm/ffn"]


def test_load_records_skips_corrupt_lines(tmp_path):
    path = tmp_path / "m.jsonl"
    path.write_text('{"step": 1}\nnot json\n\n{"step": 2}\n{"truncat')
    assert load_records(path) == [{"step": 1}, {"step": 2}]
    assert load_records(tmp_path / "missing.jsonl") == []


def _stream(tmp_path, records):
    path = tmp_path / "metrics.jsonl"
    path.write_text("".join(json.dumps(r) + "\n" for r in records))
    return path


def test_summarize_detects_anomalies(tmp_path):
    records = [
        {"kind": "manifest", "run_kind": "train", "git_sha": "abc"},
        {"step": 1, "loss": 3.0, "tokens_per_sec": 100.0},
        {"step": 2, "loss": 9.0},  # 3x spike
        {"step": 3, "loss": float("nan"), "nonfinite_grads": 2},
        {"step": 3, "val_loss": float("nan")},
        {"kind": "event", "name": "nonfinite", "t": 1.0, "step": 3},
        {"kind": "span", "name": "setup", "path": "setup", "t": 0.0, "dur_s": 1.5},
        # no footer: the run crashed
    ]
    s = summarize(load_records(_stream(tmp_path, records)))
    assert s["manifest"]["git_sha"] == "abc"
    assert s["steps"]["n"] == 3 and s["steps"]["step_range"] == [1, 3]
    assert s["spans"]["setup"]["total_s"] == pytest.approx(1.5)
    text = " | ".join(s["anomalies"])
    assert "non-finite state at step 3" in text
    assert "non-finite val_loss at step 3" in text
    assert "loss spike at step 2" in text
    assert "nonfinite event at step 3" in text
    assert "no footer" in text


def test_report_renders_clean_run(tmp_path):
    records = [
        run_manifest(kind="train", model_config=TINY),
        {"kind": "span", "name": "setup", "path": "setup", "t": 0.0, "dur_s": 0.8},
        {"step": 10, "loss": 3.0, "lr": 1e-4, "grad_norm": 0.5,
         "tokens_per_sec": 1000.0, "step_wall_s": 0.01, "mfu": 0.2,
         "grad_norm/attn": 0.3},
        {"step": 20, "loss": 2.5, "lr": 1e-4, "grad_norm": 0.4,
         "tokens_per_sec": 1200.0, "step_wall_s": 0.009, "mfu": 0.25,
         "grad_norm/attn": 0.2},
        {"step": 20, "val_loss": 2.6},
        {"kind": "footer", "t": 2.0, "clean": True, "record_counts": {}},
    ]
    text = render_report(load_records(_stream(tmp_path, records)))
    assert "== run manifest ==" in text and "kind=train" in text
    assert "steps 10..20" in text and "loss 3 -> 2.5" in text
    assert "val_loss" in text
    assert "tokens/sec" in text and "mfu" in text
    assert "setup" in text
    assert "grad_norm/attn" in text
    assert "anomalies (0)" in text and "clean footer" in text


def test_report_uses_latest_manifest_on_resumed_stream(tmp_path):
    records = [
        {"kind": "manifest", "run_kind": "train", "git_sha": "old0000"},
        {"step": 1, "loss": 3.0},
        {"kind": "footer", "t": 1.0, "clean": True, "record_counts": {}},
        {"kind": "manifest", "run_kind": "train", "git_sha": "new1111"},
        {"step": 2, "loss": 2.5},
        {"kind": "footer", "t": 2.0, "clean": True, "record_counts": {}},
    ]
    s = summarize(load_records(_stream(tmp_path, records)))
    # Latest manifest wins (matches summarize_captures.py); the render
    # flags that the stream holds multiple segments.
    assert s["manifest"]["git_sha"] == "new1111" and s["n_manifests"] == 2
    assert "latest of 2 manifests" in render_report(load_records(_stream(tmp_path, records)))


def test_report_cli_exit_codes(tmp_path, capsys):
    from bpe_transformer_tpu.telemetry.report import main as report_main

    assert report_main([]) == 2  # usage
    assert report_main([str(tmp_path / "missing.jsonl")]) == 1
    path = _stream(tmp_path, [{"step": 1, "loss": 2.0}])
    assert report_main([str(path)]) == 0
    assert "steps 1..1" in capsys.readouterr().out


def test_report_importable_without_jax(tmp_path):
    """The report tool must run on hosts with no accelerator runtime (a
    laptop summarizing a capture pulled off a pod): importing it — and the
    jax-free telemetry members — must not import jax."""
    import subprocess
    import sys as _sys
    from pathlib import Path

    script = (
        "import sys\n"
        "sys.modules['jax'] = None\n"  # any `import jax` now raises
        "from bpe_transformer_tpu.telemetry.report import summarize\n"
        "from bpe_transformer_tpu.telemetry.monitor import fold_records\n"
        "from bpe_transformer_tpu.telemetry import (\n"
        "    MetricsLogger, Telemetry, Watchdog, nonfinite_fields,\n"
        "    run_manifest, sample_resources, validate_record)\n"
        "assert 'jax_version' not in run_manifest(kind='offline')\n"
        "record = sample_resources()\n"  # degrades: RSS only, null device fields
        "assert record['host_rss_bytes'] and record['hbm_bytes_in_use'] is None\n"
        "assert validate_record(record) == []\n"
        "print('ok')\n"
    )
    repo = Path(__file__).resolve().parent.parent
    proc = subprocess.run(
        [_sys.executable, "-c", script],
        capture_output=True, text=True,
        env={"PATH": "/usr/bin:/bin", "PYTHONPATH": str(repo)},
    )
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.strip() == "ok"


# ------------------------------------------------------ resources (PR 3)


def test_sample_resources_record_shape_and_rss():
    from bpe_transformer_tpu.telemetry import sample_resources, validate_record

    record = sample_resources(step=7)
    assert record["kind"] == "resources" and record["step"] == 7
    assert validate_record(record) == []
    # Host RSS must be real on Linux CI; live buffers are an int (possibly
    # 0); CPU backends carry null HBM fields, but the KEYS are pinned.
    assert record["host_rss_bytes"] > 1024 * 1024
    assert isinstance(record["live_buffer_bytes"], int)
    assert isinstance(record["compile_events"], int)
    for key in ("hbm_bytes_in_use", "hbm_peak_bytes_in_use", "hbm_bytes_limit"):
        assert key in record


def test_compile_counter_counts_fresh_jit_compiles():
    import jax
    import jax.numpy as jnp

    from bpe_transformer_tpu.telemetry import (
        compile_events,
        install_compile_counter,
        record_compile_events,
    )

    assert install_compile_counter() is True
    assert install_compile_counter() is True  # idempotent
    before = compile_events()

    @jax.jit
    def f(x, c):
        return x * c

    f(jnp.ones(3), 2.0)  # fresh program: one compile event
    first = compile_events()
    assert first >= before + 1
    f(jnp.ones(3), 3.0)  # cache hit: no new event
    assert compile_events() == first
    f(jnp.ones((2, 2)), 2.0)  # new shape: recompile
    assert compile_events() >= first + 1
    assert record_compile_events(2) == compile_events()


def test_validate_record_flags_unknown_and_missing():
    from bpe_transformer_tpu.telemetry import validate_record

    assert validate_record({"step": 3, "loss": 1.0}) == []
    assert validate_record(
        {"kind": "span", "name": "x", "path": "x", "t": 0.0, "dur_s": 0.1}
    ) == []
    assert "undocumented" in validate_record({"kind": "mystery"})[0]
    assert "missing required" in validate_record({"kind": "span", "name": "x"})[0]


def test_telemetry_schema_tool_is_clean():
    """tools/check_telemetry_schema.py (the tier-1 gate): every kind
    emitted in the package is documented, the docs tables are current, and
    the committed fixtures validate."""
    import subprocess
    import sys as _sys
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    proc = subprocess.run(
        [_sys.executable, str(repo / "tools" / "check_telemetry_schema.py")],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "telemetry schema clean" in proc.stdout


# ------------------------------------------------- compare / regression gate


def test_compare_metrics_directions_and_thresholds():
    from bpe_transformer_tpu.telemetry.report import compare_metrics

    base = {
        "tokens_per_sec_mean": (1000.0, "higher"),
        "loss_last": (2.0, "lower"),
        "step_wall_s_mean": (0.01, "lower"),
    }
    cur = {
        "tokens_per_sec_mean": (900.0, "higher"),   # -10%: regression
        "loss_last": (1.8, "lower"),                # -10%: improvement
        "step_wall_s_mean": (0.0102, "lower"),      # +2%: within threshold
        "mfu_mean": (0.3, "higher"),                # not in baseline: skipped
    }
    rows, regressions = compare_metrics(base, cur, default_threshold_pct=5.0)
    verdicts = {r["metric"]: r["verdict"] for r in rows}
    assert verdicts == {
        "loss_last": "improved",
        "tokens_per_sec_mean": "regressed",
        "step_wall_s_mean": "ok",
    }
    assert regressions == ["tokens_per_sec_mean"]
    # A per-metric threshold override can waive the gate.
    _, regressions = compare_metrics(
        base, cur, default_threshold_pct=5.0,
        thresholds={"tokens_per_sec_mean": 15.0},
    )
    assert regressions == []


FIXTURES = Path(__file__).resolve().parent / "fixtures"


def test_report_compare_fixture_pair_gates_regression(capsys):
    """ACCEPTANCE: the committed fixture pair encodes a known throughput/
    MFU/HBM regression; `bpe-tpu report --compare` exits 3 on it, 0 in the
    improving direction, and 0 when thresholds waive it."""
    from bpe_transformer_tpu.telemetry.report import main as report_main

    base = str(FIXTURES / "compare_base.jsonl")
    regressed = str(FIXTURES / "compare_regressed.jsonl")
    assert report_main([regressed, "--compare", base]) == 3
    out = capsys.readouterr().out
    assert "== compare vs" in out and "regressed" in out
    assert "tokens_per_sec_mean" in out and "hbm_peak_bytes" in out

    # The improving direction passes the gate (deltas flagged "improved").
    assert report_main([base, "--compare", regressed]) == 0
    assert "improved" in capsys.readouterr().out

    # Thresholds are configurable: wide enough, the same pair passes.
    assert report_main(
        [regressed, "--compare", base, "--threshold-pct", "50"]
    ) == 0
    # ...and a bad per-metric threshold is a usage error, not a silent skip.
    assert report_main(
        [regressed, "--compare", base, "--threshold", "typo_metric=5"]
    ) == 2


def test_report_baseline_capture_gate(tmp_path, capsys):
    """--baseline gates a stream against a bench capture JSON (and a
    capture against a previous capture — the tpu_queue.sh self-report)."""
    from bpe_transformer_tpu.telemetry.report import main as report_main

    capture = tmp_path / "tpu_capture_test.json"
    capture.write_text(json.dumps(
        {"metric": "tok/s", "value": 1500000.0, "mfu": 0.28,
         "platform": "tpu", "final_val_loss": 2.7}
    ))
    regressed = str(FIXTURES / "compare_regressed.jsonl")
    assert report_main([regressed, "--baseline", str(capture)]) == 3
    assert "regressed" in capsys.readouterr().out

    slower = tmp_path / "tpu_capture_prev.json"
    slower.write_text(json.dumps(
        {"metric": "tok/s", "value": 1000000.0, "mfu": 0.2, "platform": "tpu"}
    ))
    assert report_main([str(capture), "--baseline", str(slower)]) == 0
    out = capsys.readouterr().out
    assert "== bench capture" in out and "improved" in out
    assert report_main([str(slower), "--baseline", str(capture)]) == 3


def test_report_graceful_on_empty_and_manifest_less(tmp_path, capsys):
    """Satellite: an empty (or corrupt-only) stream exits 1 with a clear
    message — never a traceback — and a manifest-less stream still renders
    with an explicit '(no manifest record)' line."""
    from bpe_transformer_tpu.telemetry.report import main as report_main

    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert report_main([str(empty)]) == 1
    err = capsys.readouterr().err
    assert "no readable records" in err and "Traceback" not in err

    corrupt = tmp_path / "corrupt.jsonl"
    corrupt.write_text("not json at all\n{truncat")
    assert report_main([str(corrupt)]) == 1

    manifestless = tmp_path / "manifestless.jsonl"
    manifestless.write_text(json.dumps({"step": 1, "loss": 2.0}) + "\n")
    assert report_main([str(manifestless)]) == 0
    assert "(no manifest record)" in capsys.readouterr().out


# ------------------------------------------------------------------ monitor


def test_monitor_fold_records_builds_operational_state():
    from bpe_transformer_tpu.telemetry.monitor import fold_records, render_frame

    state = fold_records([
        {"kind": "manifest", "run_kind": "train",
         "devices": {"count": 8, "kind": "cpu"}},
        {"step": 10, "loss": 3.0, "tokens_per_sec": 500.0, "mfu": 0.1},
        {"step": 20, "loss": 2.5, "tokens_per_sec": 600.0, "mfu": 0.12},
        {"kind": "resources", "time_unix": 0.0, "host_rss_bytes": 2**30,
         "live_buffer_bytes": 2**20, "compile_events": 4,
         "hbm_bytes_in_use": None, "hbm_peak_bytes_in_use": None,
         "hbm_bytes_limit": None},
        {"kind": "event", "name": "watchdog_hang", "t": 5.0},
        {"kind": "footer", "t": 9.0, "clean": True, "record_counts": {}},
    ])
    assert state["step"] == 20 and state["loss"] == 2.5
    assert state["host_rss_bytes"] == 2**30
    assert "hbm_bytes_in_use" not in state  # null never overwrites
    assert state["anomalies"] == 1 and state["last_anomaly"] == "watchdog_hang"
    assert state["footer_clean"] is True
    frame = render_frame(state, "test.jsonl")
    assert "step 20" in frame and "loss 2.5" in frame
    assert "rss 1,024.0 MiB" in frame
    assert "anomalies 1" in frame and "cleanly" in frame
    # Incremental fold continues from prior state (the tail path).
    state2 = fold_records([{"step": 30, "loss": 2.4}], state)
    assert state2["step"] == 30 and state2["anomalies"] == 1


def test_monitor_prometheus_roundtrip():
    """render_prometheus -> parse_prometheus -> fold_prometheus closes the
    loop: the monitor reconstructs serve state from a real scrape body."""
    from bpe_transformer_tpu.serving.metrics import (
        ServingMetrics,
        render_prometheus,
    )
    from bpe_transformer_tpu.telemetry.monitor import (
        fold_prometheus,
        parse_prometheus,
        render_frame,
    )

    m = ServingMetrics()
    m.on_submit(); m.on_submit(); m.on_reject()
    m.on_finish("length"); m.on_finish("stop")
    m.observe_phase("decode", 0.2)
    m.observe_phase("queue_wait", 0.004)
    text = render_prometheus(
        m,
        {"queue_depth": 1, "active_slots": 2, "slots": 4, "ticks": 9,
         "tokens_emitted": 55, "compiled_programs": 3},
        {"compile_events": 7, "host_rss_bytes": 2**20,
         "live_buffer_bytes": None, "hbm_bytes_in_use": None,
         "hbm_peak_bytes_in_use": None, "hbm_bytes_limit": None},
    )
    state = fold_prometheus(parse_prometheus(text))
    assert state["requests_finished"] == 2
    assert state["requests_rejected"] == 1
    assert state["queue_depth"] == 1 and state["slots"] == 4
    assert state["tokens_total"] == 55
    assert state["compile_events"] == 7
    assert "hbm_bytes_in_use" not in state  # null gauges never rendered
    frame = render_frame(state, "http://x/metrics")
    assert "slots 2/4" in frame and "queue 1" in frame and "rejected 1" in frame


def test_monitor_histogram_consistency():
    from bpe_transformer_tpu.serving.metrics import LatencyHistogram

    h = LatencyHistogram(buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    cumulative = h.cumulative()
    assert [c for _, c in cumulative] == [1, 2, 3, 4]
    assert cumulative[-1][0] == math.inf and cumulative[-1][1] == h.count == 4
    assert h.sum == pytest.approx(5.555)
    assert h.percentile(0.5) == 0.1
    assert h.percentile(1.0) == 1.0  # +Inf clamps to the last finite bound
    h.observe(float("nan"))  # ignored, not corrupted
    assert h.count == 4


def test_monitor_cli_once_smoke(tmp_path):
    """Satellite: `bpe-tpu monitor <stream> --once` renders one frame and
    exits 0 in a non-tty subprocess, without jax importable."""
    import subprocess
    import sys as _sys

    repo = Path(__file__).resolve().parent.parent
    fixture = repo / "tests" / "fixtures" / "serving_tiny.jsonl"
    proc = subprocess.run(
        [
            _sys.executable, "-c",
            "import sys; sys.modules['jax'] = None\n"
            "from bpe_transformer_tpu.telemetry.monitor import main\n"
            f"sys.exit(main([{str(fixture)!r}, '--once']))",
        ],
        capture_output=True, text=True, timeout=120,
        env={"PATH": "/usr/bin:/bin", "PYTHONPATH": str(repo)},
    )
    assert proc.returncode == 0, proc.stderr
    assert "bpe-tpu monitor" in proc.stdout
    assert "requests 3" in proc.stdout

    # Usage errors are crisp: no source, or two sources.
    from bpe_transformer_tpu.telemetry.monitor import main as monitor_main

    assert monitor_main([]) == 2
    assert monitor_main(["x.jsonl", "--url", "host:1"]) == 2
    assert monitor_main([str(tmp_path / "missing.jsonl")]) == 1


def test_monitor_url_mode_against_live_endpoint(tmp_path):
    """--url mode: the monitor scrapes a real HTTP /metrics endpoint (a
    stub server rendering ServingMetrics) and folds it into a frame."""
    import threading
    from http.server import BaseHTTPRequestHandler, HTTPServer

    from bpe_transformer_tpu.serving.metrics import (
        ServingMetrics,
        render_prometheus,
    )
    from bpe_transformer_tpu.telemetry.monitor import UrlSource

    m = ServingMetrics()
    m.on_submit()
    m.on_finish("length")
    m.observe_phase("decode", 0.1)
    body = render_prometheus(
        m, {"queue_depth": 0, "active_slots": 0, "slots": 2, "ticks": 3,
            "tokens_emitted": 12, "compiled_programs": 2},
    )

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            data = body.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

    server = HTTPServer(("127.0.0.1", 0), Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        source = UrlSource(f"127.0.0.1:{server.server_address[1]}")
        state = source.refresh()
        assert state["requests_finished"] == 1
        assert state["tokens_total"] == 12
    finally:
        server.shutdown()
        server.server_close()
        thread.join(timeout=10)


# -------------------------------------------------- loop integration (CPU)


HP = dict(
    max_learning_rate=1e-3,
    min_learning_rate=1e-4,
    warmup_iters=2,
    cosine_cycle_iters=50,
)


@pytest.fixture(scope="module")
def byte_data():
    text = b"the quick brown fox. " * 2000
    return np.frombuffer(text, dtype=np.uint8).astype(np.uint16)


def test_train_emits_unified_stream_and_report_reads_it(tmp_path, byte_data):
    """The acceptance run: health stats + spans + watchdog on a short CPU
    training run produce one self-describing JSONL — manifest header, span
    records, per-layer-group grad norms, watchdog-clean footer — that
    `bpe-tpu report` summarizes."""
    from bpe_transformer_tpu.training import LoopConfig, TrainHParams, train

    jsonl = tmp_path / "metrics.jsonl"
    loop = LoopConfig(
        steps=8,
        batch_size=8,
        log_every=4,
        eval_every=8,
        eval_batches=1,
        checkpoint_every=100,
        metrics_jsonl=str(jsonl),
        health_stats=True,
        watchdog=True,
    )
    summary = train(
        TINY, TrainHParams(**HP), loop, byte_data, byte_data,
        log_fn=lambda *_: None,
    )
    assert np.isfinite(summary["final_train_loss"])
    records = load_records(jsonl)

    manifest = records[0]
    assert manifest["kind"] == "manifest" and manifest["run_kind"] == "train"
    assert manifest["model_config"]["d_model"] == TINY.d_model
    assert manifest["loop_config"]["health_stats"] is True

    spans = {r["path"] for r in records if r.get("kind") == "span"}
    assert {"setup", "compile_first_step"} <= spans
    assert any(p.startswith("eval") for p in spans)

    steps = [r for r in records if "kind" not in r and "loss" in r]
    assert [r["step"] for r in steps] == [4, 8]
    for r in steps:
        assert r["nonfinite_loss"] == 0
        assert r["grad_norm/attn"] > 0 and r["param_norm/ffn"] > 0
        assert r["tokens_per_sec"] > 0 and r["step_wall_s"] > 0

    # ACCEPTANCE (PR 3): the run emits kind="resources" records at every
    # log boundary with non-null host RSS (HBM fields null on CPU), at
    # zero extra host syncs — they ride the existing metric fetch.
    resources = [r for r in records if r.get("kind") == "resources"]
    assert [r["step"] for r in resources] == [4, 8]
    for r in resources:
        assert r["host_rss_bytes"] > 0
        assert isinstance(r["compile_events"], int) and r["compile_events"] >= 1
        assert "hbm_bytes_in_use" in r and "live_buffer_bytes" in r

    footer = records[-1]
    assert footer["kind"] == "footer" and footer["clean"] is True
    assert footer["watchdog_hang_events"] == 0
    assert footer["watchdog_nonfinite_events"] == 0
    # Step and val records flow through the narrator too, so the footer's
    # record_counts cross-checks the WHOLE stream (truncation detection):
    # 2 step records + 1 val record, all under the default "metric:" key.
    assert footer["record_counts"]["metric:"] == 3

    text = render_report(records)
    assert "anomalies (0)" in text and "grad_norm/attn" in text


def test_nan_injection_fires_watchdog_raise_policy(tmp_path, byte_data):
    """Synthetic NaN: an absurd LR overflows the params within a step or
    two; the health stats surface it at the next log boundary and the
    watchdog's "raise" policy dumps the record then stops the run."""
    from bpe_transformer_tpu.training import LoopConfig, TrainHParams, train

    jsonl = tmp_path / "metrics.jsonl"
    loop = LoopConfig(
        steps=12,
        batch_size=8,
        log_every=2,
        eval_every=100,
        checkpoint_every=100,
        metrics_jsonl=str(jsonl),
        health_stats=True,
        watchdog=True,
        watchdog_policy="raise",
    )
    hot = TrainHParams(
        max_learning_rate=1e30, min_learning_rate=1e30,
        warmup_iters=0, cosine_cycle_iters=50,
    )
    with pytest.raises(NonFiniteError):
        train(TINY, hot, loop, byte_data, log_fn=lambda *_: None)
    records = load_records(jsonl)
    events = [r for r in records if r.get("kind") == "event"]
    assert any(e["name"] == "nonfinite" for e in events)
    # The dump carries the offending record, and the footer is unclean.
    dump = next(e for e in events if e["name"] == "nonfinite")
    assert dump["fields"] and dump["record"]["step"] == dump["step"]
    footer = records[-1]
    assert footer["kind"] == "footer" and footer["clean"] is False
    assert footer["watchdog_nonfinite_events"] == 1
    # The report surfaces the whole story from the file alone.
    text = render_report(records)
    assert "nonfinite event" in text and "unclean" in text


def test_nan_injection_skip_policy_keeps_training(tmp_path, byte_data):
    from bpe_transformer_tpu.training import LoopConfig, TrainHParams, train

    jsonl = tmp_path / "metrics.jsonl"
    loop = LoopConfig(
        steps=6,
        batch_size=8,
        log_every=2,
        eval_every=100,
        checkpoint_every=100,
        metrics_jsonl=str(jsonl),
        health_stats=True,
        watchdog=True,
        watchdog_policy="skip",
    )
    hot = TrainHParams(
        max_learning_rate=1e30, min_learning_rate=1e30,
        warmup_iters=0, cosine_cycle_iters=50,
    )
    train(TINY, hot, loop, byte_data, log_fn=lambda *_: None)  # must not raise
    records = load_records(jsonl)
    footer = records[-1]
    assert footer["kind"] == "footer" and footer["clean"] is True
    assert footer["watchdog_nonfinite_events"] >= 1


# ----------------------------------------------- dynamics introspection


def test_dynamics_paths_labels_and_localization():
    """Pure helpers: tensor paths, layer labels, and the params -> act ->
    grads localization priority in flatten_dynamics."""
    import jax

    from bpe_transformer_tpu.telemetry.dynamics import (
        dynamics_metrics,
        flatten_dynamics,
        layer_label,
        per_layer_norms,
    )

    assert layer_label("layers.3.attn.q_proj") == "layers.3"
    assert layer_label("token_embeddings") == "token_embeddings"

    params = {
        "layers": [
            {"ffn": {"w1": jnp.ones((2, 2))}},
            {"ffn": {"w1": jnp.full((2, 2), float("nan"))}},
        ],
        "lm_head": jnp.ones((3,)),
    }
    norms = per_layer_norms(params)
    assert set(norms) == {"layers.0", "layers.1", "lm_head"}
    assert norms["layers.0"] == pytest.approx(2.0)

    grads = jax.tree_util.tree_map(jnp.zeros_like, params)
    clean = jax.tree_util.tree_map(jnp.ones_like, params)
    dyn = jax.device_get(dynamics_metrics(grads, params, clean))
    flat = flatten_dynamics(dyn)
    # The NaN lives in the step's INPUT params; only nonzero counts emit.
    assert flat["nonfinite_params/layers.1.ffn.w1"] == 4
    assert flat["first_nonfinite"] == "params/layers.1.ffn.w1"
    assert not any(k.startswith("nonfinite_grads/") for k in flat)
    assert flat["update_ratio/layers.0"] >= 0

    # Clean trees carry no localization keys at all.
    flat_clean = flatten_dynamics(
        jax.device_get(dynamics_metrics(grads, clean, clean))
    )
    assert "first_nonfinite" not in flat_clean
    assert not any(k.startswith("nonfinite_") for k in flat_clean)

    # Activation localization outranks gradients (the finite-params,
    # overflowing-activation scenario) but not params.
    act = {
        "rms": jnp.ones((2,)),
        "absmax": jnp.ones((2,)),
        "nonfinite": jnp.array([0, 7], jnp.int32),
        "attn_entropy": jnp.ones((2,)),
    }
    bad_grads = jax.tree_util.tree_map(
        lambda p: jnp.full_like(p, float("inf")), params
    )
    flat_act = flatten_dynamics(
        jax.device_get(dynamics_metrics(bad_grads, clean, clean, act))
    )
    assert flat_act["first_nonfinite"] == "act/layers.1"
    assert flat_act["act_nonfinite/layers.1"] == 7
    assert flat_act["attn_entropy/layers.0"] == pytest.approx(1.0)


def test_dynamics_enabled_train_step_exports_per_layer_stats():
    import jax

    from bpe_transformer_tpu.models import init_params
    from bpe_transformer_tpu.optim import adamw_init
    from bpe_transformer_tpu.telemetry.dynamics import flatten_dynamics
    from bpe_transformer_tpu.training.train_step import (
        TrainHParams,
        make_train_step,
    )

    params = init_params(jax.random.PRNGKey(0), TINY)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, TINY.vocab_size, size=(4, TINY.context_length))
    x, y = jnp.asarray(ids), jnp.asarray(np.roll(ids, -1, axis=1))

    # Default step: no dynamics key, metrics unchanged.
    _, _, metrics = make_train_step(TINY, TrainHParams())(
        params, adamw_init(params), x, y
    )
    assert "dynamics" not in metrics

    params = init_params(jax.random.PRNGKey(0), TINY)
    step = make_train_step(TINY, TrainHParams(), dynamics=True)
    _, _, metrics = step(params, adamw_init(params), x, y)
    flat = flatten_dynamics(jax.device_get(metrics["dynamics"]))
    for layer in ("layers.0", "layers.1", "token_embeddings", "lm_head"):
        assert flat[f"grad_norm/{layer}"] > 0
        assert flat[f"param_norm/{layer}"] > 0
        assert flat[f"update_ratio/{layer}"] >= 0
    for i in range(TINY.num_layers):
        assert math.isfinite(flat[f"act_rms/layers.{i}"])
        assert flat[f"act_absmax/layers.{i}"] > 0
        # Causal softmax entropy over a 16-token context: strictly inside
        # (0, log 16].
        assert 0 < flat[f"attn_entropy/layers.{i}"] <= math.log(16) + 1e-5
    assert "first_nonfinite" not in flat  # clean run


@pytest.mark.slow
def test_dynamics_rides_scanned_and_grad_accum_variants():
    import jax

    from bpe_transformer_tpu.models import init_params
    from bpe_transformer_tpu.optim import adamw_init
    from bpe_transformer_tpu.telemetry.dynamics import flatten_dynamics
    from bpe_transformer_tpu.training.train_step import (
        TrainHParams,
        make_grad_accum_train_step,
        make_scanned_train_step,
    )

    hp = TrainHParams(warmup_iters=0)
    params = init_params(jax.random.PRNGKey(0), TINY)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, TINY.vocab_size, size=(2, 4, TINY.context_length))
    xs, ys = jnp.asarray(ids), jnp.asarray(np.roll(ids, -1, axis=2))

    step = make_scanned_train_step(TINY, hp, 2, dynamics=True)
    _, _, metrics = step(params, adamw_init(params), xs, ys)
    flat = flatten_dynamics(jax.device_get(metrics["dynamics"]))
    assert flat["grad_norm/layers.1"] > 0
    assert flat["attn_entropy/layers.0"] > 0  # act taps ride the scan body

    params = init_params(jax.random.PRNGKey(0), TINY)
    step = make_grad_accum_train_step(TINY, hp, 2, dynamics=True)
    _, _, metrics = step(params, adamw_init(params), xs, ys)
    flat = flatten_dynamics(jax.device_get(metrics["dynamics"]))
    assert flat["grad_norm/layers.1"] > 0
    assert flat["update_ratio/layers.0"] > 0
    # The accumulation scan carries loss+grads, not activation taps.
    assert not any(k.startswith(("act_rms/", "attn_entropy/")) for k in flat)


def test_dynamics_record_validates_against_schema():
    from bpe_transformer_tpu.telemetry import validate_record
    from bpe_transformer_tpu.telemetry.dynamics import dynamics_record

    record = dynamics_record(
        50, {"grad_norm/layers.0": 0.5, "first_nonfinite": "params/x"}
    )
    assert record["kind"] == "dynamics" and record["step"] == 50
    assert validate_record(record) == []
    assert validate_record({"kind": "dynamics"})  # step is required


def test_dynamics_every_validation():
    from bpe_transformer_tpu.training import LoopConfig, TrainHParams, train

    data = np.zeros(10_000, np.uint16)
    loop = LoopConfig(steps=2, batch_size=8, log_every=2, dynamics_every=3)
    with pytest.raises(ValueError, match="multiple of log_every"):
        train(TINY, TrainHParams(**HP), loop, data)
    loop = LoopConfig(
        steps=2, batch_size=8, parallel="sp", dynamics_every=2, log_every=2
    )
    with pytest.raises(ValueError, match="dynamics_every"):
        train(TINY, TrainHParams(**HP), loop, data)
    with pytest.raises(ValueError, match=">= 0"):
        train(
            TINY, TrainHParams(**HP),
            LoopConfig(steps=2, batch_size=8, dynamics_every=-1), data,
        )


def test_health_stats_rejected_for_sp_and_pp():
    from bpe_transformer_tpu.training import LoopConfig, TrainHParams, train

    loop = LoopConfig(steps=2, batch_size=8, parallel="sp", health_stats=True)
    with pytest.raises(ValueError, match="health_stats"):
        train(TINY, TrainHParams(**HP), loop, np.zeros(10_000, np.uint16))


def test_bad_watchdog_policy_rejected_before_sinks_open(tmp_path):
    """An invalid policy must fail fast — before the metrics JSONL (or a
    wandb run) is opened, so nothing leaks."""
    from bpe_transformer_tpu.training import LoopConfig, TrainHParams, train

    jsonl = tmp_path / "metrics.jsonl"
    loop = LoopConfig(
        steps=2, batch_size=8, metrics_jsonl=str(jsonl),
        watchdog=True, watchdog_policy="warn",
    )
    with pytest.raises(ValueError, match="watchdog_policy"):
        train(TINY, TrainHParams(**HP), loop, np.zeros(10_000, np.uint16))
    assert not jsonl.exists()


# ------------------------------------------- dynamics: loop integration


def _counting_train(monkeypatch, byte_data, tmp_path, dynamics_every):
    """Run a short training with jax.device_get / block_until_ready call
    counting; returns (records, counts)."""
    import jax

    from bpe_transformer_tpu.training import LoopConfig, TrainHParams, train

    counts = {"device_get": 0, "block_until_ready": 0}
    real_get, real_block = jax.device_get, jax.block_until_ready

    def counting_get(x):
        counts["device_get"] += 1
        return real_get(x)

    def counting_block(x):
        counts["block_until_ready"] += 1
        return real_block(x)

    monkeypatch.setattr(jax, "device_get", counting_get)
    monkeypatch.setattr(jax, "block_until_ready", counting_block)
    jsonl = tmp_path / f"dyn_{dynamics_every}.jsonl"
    loop = LoopConfig(
        steps=8,
        batch_size=8,
        log_every=4,
        eval_every=100,
        checkpoint_every=100,
        metrics_jsonl=str(jsonl),
        dynamics_every=dynamics_every,
    )
    train(TINY, TrainHParams(**HP), loop, byte_data, log_fn=lambda *_: None)
    monkeypatch.setattr(jax, "device_get", real_get)
    monkeypatch.setattr(jax, "block_until_ready", real_block)
    return load_records(jsonl), counts


def test_dynamics_loop_emits_records_at_zero_extra_fetches(
    monkeypatch, tmp_path, byte_data
):
    """ACCEPTANCE: with --dynamics-every the stream gains kind="dynamics"
    records at the dynamics cadence — and the number of device fetches /
    sync barriers is IDENTICAL to a run with the flag off (the dynamics
    pytree rides the existing log-cadence fetch)."""
    from bpe_transformer_tpu.telemetry import validate_record

    records_off, counts_off = _counting_train(
        monkeypatch, byte_data, tmp_path, dynamics_every=0
    )
    records_on, counts_on = _counting_train(
        monkeypatch, byte_data, tmp_path, dynamics_every=4
    )
    assert counts_on == counts_off  # zero additional device→host syncs

    dynamics = [r for r in records_on if r.get("kind") == "dynamics"]
    assert [r["step"] for r in dynamics] == [4, 8]
    for r in dynamics:
        assert validate_record(r) == []
        assert r["grad_norm/layers.0"] > 0
        assert r["attn_entropy/layers.1"] > 0
        assert "first_nonfinite" not in r  # clean run

    # Flag off: no dynamics records, and the step records carry no
    # dynamics-derived keys — the schema is byte-identical to before.
    assert not [r for r in records_off if r.get("kind") == "dynamics"]
    steps_off = [r for r in records_off if "kind" not in r and "loss" in r]
    dyn_prefixes = (
        "update_ratio/", "act_rms/", "act_absmax/", "attn_entropy/",
        "nonfinite_params/", "nonfinite_grads/", "act_nonfinite/",
    )
    for r in steps_off:
        assert not any(k.startswith(dyn_prefixes) for k in r)
        assert "nonfinite_path" not in r


def test_dynamics_localizes_nan_seeded_layer(tmp_path, byte_data):
    """ACCEPTANCE: a run whose params are seeded with a NaN in layer 1's
    ffn.w1 produces a watchdog nonfinite event AND a report callout naming
    that tensor path — the documented forensic workflow (resume from a
    checkpoint at --dynamics-every 1 --log-every 1)."""
    import jax

    from bpe_transformer_tpu.checkpointing import save_checkpoint
    from bpe_transformer_tpu.models import init_params
    from bpe_transformer_tpu.optim import adamw_init
    from bpe_transformer_tpu.training import LoopConfig, TrainHParams, train

    params = init_params(jax.random.PRNGKey(0), TINY)
    w1 = np.asarray(params["layers"][1]["ffn"]["w1"]).copy()
    w1[0, 0] = np.nan
    params["layers"][1]["ffn"]["w1"] = jnp.asarray(w1)
    ckpt = tmp_path / "nan.ckpt"
    save_checkpoint(ckpt, params=params, opt_state=adamw_init(params), iteration=0)

    jsonl = tmp_path / "nan.jsonl"
    loop = LoopConfig(
        steps=4,
        batch_size=8,
        log_every=1,
        eval_every=100,
        checkpoint_every=100,
        metrics_jsonl=str(jsonl),
        dynamics_every=1,
        watchdog=True,
        watchdog_policy="raise",
    )
    with pytest.raises(NonFiniteError, match=r"params/layers\.1\.ffn\.w1"):
        train(
            TINY, TrainHParams(**HP), loop, byte_data,
            resume_from=ckpt, log_fn=lambda *_: None,
        )
    records = load_records(jsonl)
    event = next(
        r for r in records if r.get("kind") == "event" and r["name"] == "nonfinite"
    )
    assert event["path"] == "params/layers.1.ffn.w1"
    dynamics = [r for r in records if r.get("kind") == "dynamics"]
    assert dynamics[0]["first_nonfinite"] == "params/layers.1.ffn.w1"
    assert dynamics[0]["nonfinite_params/layers.1.ffn.w1"] == 1
    text = render_report(records)
    assert "localized to params/layers.1.ffn.w1" in text


# ------------------------------------- dynamics: fixture, report, monitor


def test_report_dynamics_fixture_pins_section_and_compare(capsys):
    """The committed dynamics_tiny.jsonl pins the report Dynamics section
    (per-layer table + localization callout) and still feeds the --compare
    gate; a stream with NO dynamics records renders no section and exits
    cleanly."""
    from bpe_transformer_tpu.telemetry.report import main as report_main

    fixture = str(FIXTURES / "dynamics_tiny.jsonl")
    assert report_main([fixture]) == 0
    out = capsys.readouterr().out
    assert "== dynamics (2 records, steps 50..100) ==" in out
    assert "layers.0" in out and "layers.1" in out
    assert "! first non-finite: params/layers.1.ffn.w1 at step 100" in out
    assert "nonfinite event at step 100 localized to params/layers.1.ffn.w1" in out

    # Self-compare: shared metrics, zero delta, exit 0.
    assert report_main([fixture, "--compare", fixture]) == 0
    assert "no regressions" in capsys.readouterr().out

    # A dynamics-free stream: clean exit, no Dynamics section.
    plain = str(FIXTURES / "telemetry_tiny.jsonl")
    assert report_main([plain]) == 0
    assert "== dynamics" not in capsys.readouterr().out


def test_monitor_once_renders_dynamics_table(tmp_path):
    """Satellite: `bpe-tpu monitor <dynamics stream> --once` renders the
    per-layer table without jax importable."""
    import subprocess
    import sys as _sys

    repo = Path(__file__).resolve().parent.parent
    fixture = repo / "tests" / "fixtures" / "dynamics_tiny.jsonl"
    proc = subprocess.run(
        [
            _sys.executable, "-c",
            "import sys; sys.modules['jax'] = None\n"
            "from bpe_transformer_tpu.telemetry.monitor import main\n"
            f"sys.exit(main([{str(fixture)!r}, '--once']))",
        ],
        capture_output=True, text=True, timeout=120,
        env={"PATH": "/usr/bin:/bin", "PYTHONPATH": str(repo)},
    )
    assert proc.returncode == 0, proc.stderr
    assert "per-layer introspection (step 100)" in proc.stdout
    assert "layers.0" in proc.stdout and "token_embeddings" in proc.stdout
    assert "nonfinite params/layers.1.ffn.w1" in proc.stdout or "anomalies" in proc.stdout


# --------------------------------------------------- chrome trace export


def test_trace_events_spans_and_counters():
    from bpe_transformer_tpu.telemetry.trace import trace_events

    records = [
        {"kind": "manifest", "run_kind": "train",
         "time_utc": "2026-08-03T00:00:00+00:00"},
        {"kind": "span", "name": "setup", "path": "setup", "t": 0.0,
         "dur_s": 1.0},
        {"kind": "span", "name": "resume", "path": "setup/resume", "t": 0.2,
         "dur_s": 0.5, "step": 3},
        {"kind": "engine", "t": 2.0, "active_slots": 3, "queue_depth": 1,
         "tokens_per_sec": 500.0, "tokens_total": 10, "ticks": 5,
         "requests_finished": 2, "compiled_programs": 4},
        {"kind": "resources", "time_unix": 1785542402.5,
         "host_rss_bytes": 2**30, "live_buffer_bytes": None,
         "compile_events": 7, "hbm_bytes_in_use": None,
         "hbm_peak_bytes_in_use": None, "hbm_bytes_limit": None},
    ]
    events = trace_events(records)
    spans = [e for e in events if e["ph"] == "X"]
    assert [e["name"] for e in spans] == ["setup", "resume"]
    # Distinct paths get distinct named lanes; attrs ride through as args.
    assert spans[0]["tid"] != spans[1]["tid"]
    assert spans[1]["args"] == {"step": 3}
    assert spans[1]["ts"] == pytest.approx(0.2e6) and spans[1]["dur"] == pytest.approx(0.5e6)
    names = {
        e["args"]["name"]
        for e in events
        if e["ph"] == "M" and e["name"] == "thread_name"
    }
    assert {"setup", "setup/resume"} <= names

    counters = {e["name"]: e for e in events if e["ph"] == "C"}
    assert counters["engine"]["args"]["tokens_per_sec"] == 500.0
    assert counters["engine"]["ts"] == pytest.approx(2e6)
    # resources re-based against the manifest's time_utc: the fixture
    # sample is 2.5 s after the 2026-08-03T00:00:00+00:00 epoch... which is
    # seconds-since-epoch arithmetic — just pin non-negativity and args.
    assert counters["resources"]["ts"] >= 0
    assert counters["resources"]["args"] == {
        "host_rss_bytes": 2**30, "compile_events": 7,
    }


def test_report_trace_cli_writes_chrome_trace(tmp_path, capsys):
    from bpe_transformer_tpu.telemetry.report import main as report_main

    fixture = str(FIXTURES / "dynamics_tiny.jsonl")
    out = tmp_path / "trace.json"
    assert report_main([fixture, "--trace", str(out)]) == 0
    assert "wrote" in capsys.readouterr().out
    payload = json.loads(out.read_text())
    assert payload["traceEvents"]
    kinds = {e["ph"] for e in payload["traceEvents"]}
    assert "X" in kinds and "C" in kinds

    # --trace on a bench capture (not a stream) is a crisp usage error.
    capture = tmp_path / "cap.json"
    capture.write_text(json.dumps({"metric": "tok/s", "value": 1.0}))
    assert report_main([str(capture), "--trace", str(tmp_path / "t.json")]) == 2


# ------------------------------------------- attribution: cost model, probe


def test_time_call_and_program_cost_cpu_smoke():
    """The shared measurement path (telemetry.attribution): XLA
    cost_analysis of an AOT-compiled program yields positive flops/bytes
    on CPU too (the cost model is tier-1-testable), and time_call returns
    a positive mean ms."""
    import jax

    from bpe_transformer_tpu.telemetry.attribution import (
        program_cost,
        time_call,
    )

    def f(a, b):
        return (a @ b).sum()

    x = jnp.ones((64, 128))
    y = jnp.ones((128, 32))
    compiled = jax.jit(f).lower(x, y).compile()
    cost = program_cost(compiled)
    assert cost["flops"] and cost["flops"] > 0
    assert cost["bytes_accessed"] and cost["bytes_accessed"] > 0
    assert time_call(compiled, x, y, iters=2, warmup=1) > 0


def test_roofline_verdicts_and_unknown_device():
    from bpe_transformer_tpu.telemetry.attribution import roofline

    # TPU v4: peak 275 TF/s over 1228 GB/s -> ridge ~223.9 flops/byte.
    high = roofline(1e12, 1e9, "TPU v4", name="matmul")  # AI 1000
    low = roofline(1e9, 1e9, "TPU v4", name="gather")  # AI 1
    assert high["bound"] == "compute-bound"
    assert low["bound"] == "memory-bound"
    assert high["ridge_flops_per_byte"] == pytest.approx(223.9, abs=0.1)
    # No peak-table entry (CPU): intensity still reported, verdict honest.
    unknown = roofline(1e12, 1e9, "cpu")
    assert unknown["bound"] == "unknown"
    assert unknown["arithmetic_intensity"] == 1000.0
    # Degenerate counters: no crash, no fake verdict.
    assert roofline(None, None, "TPU v4")["bound"] == "unknown"


def test_peak_tables_and_warn_once_on_unknown_kind():
    import warnings

    from bpe_transformer_tpu.utils import flops as flops_mod

    assert flops_mod.peak_flops_per_chip("TPU v5p") == 459e12
    assert flops_mod.peak_flops_per_chip("TPU v6e") == 918e12
    assert flops_mod.peak_hbm_bytes_per_sec("TPU v4") == 1228e9
    # Unknown TPU generation: None + exactly ONE warning per kind (a
    # silent None quietly disables MFU/roofline for the whole run).
    flops_mod._warned_unknown_kinds.discard("TPU v99")
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        assert flops_mod.peak_flops_per_chip("TPU v99") is None
        assert flops_mod.peak_flops_per_chip("TPU v99") is None
    assert len([w for w in caught if "TPU v99" in str(w.message)]) == 1
    # CPU/GPU backends are not TPU generations — no warning noise there.
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        assert flops_mod.peak_flops_per_chip("cpu") is None
    assert not caught


def test_attribution_every_validation():
    from bpe_transformer_tpu.training import LoopConfig, TrainHParams, train

    data = np.zeros(10_000, np.uint16)
    with pytest.raises(ValueError, match="attribution_every"):
        train(
            TINY, TrainHParams(**HP),
            LoopConfig(steps=2, batch_size=8, attribution_every=-1),
            data,
        )
    with pytest.raises(ValueError, match="multiple of log_every"):
        train(
            TINY, TrainHParams(**HP),
            LoopConfig(
                steps=4, batch_size=8, log_every=4, attribution_every=3
            ),
            data,
        )


def _counting_attr_train(monkeypatch, byte_data, tmp_path, attribution_every):
    """Like _counting_train, parameterized on attribution_every."""
    import jax

    from bpe_transformer_tpu.training import LoopConfig, TrainHParams, train

    counts = {"device_get": 0, "block_until_ready": 0}
    real_get, real_block = jax.device_get, jax.block_until_ready

    def counting_get(x):
        counts["device_get"] += 1
        return real_get(x)

    def counting_block(x):
        counts["block_until_ready"] += 1
        return real_block(x)

    monkeypatch.setattr(jax, "device_get", counting_get)
    monkeypatch.setattr(jax, "block_until_ready", counting_block)
    jsonl = tmp_path / f"attr_{attribution_every}.jsonl"
    loop = LoopConfig(
        steps=8,
        batch_size=8,
        log_every=4,
        eval_every=100,
        checkpoint_every=100,
        metrics_jsonl=str(jsonl),
        attribution_every=attribution_every,
    )
    train(TINY, TrainHParams(**HP), loop, byte_data, log_fn=lambda *_: None)
    monkeypatch.setattr(jax, "device_get", real_get)
    monkeypatch.setattr(jax, "block_until_ready", real_block)
    return load_records(jsonl), counts


@pytest.mark.slow
def test_attribution_loop_emits_records_at_bounded_fetch_cost(
    monkeypatch, tmp_path, byte_data
):
    """ACCEPTANCE: --attribution-every emits kind="attribution" records
    whose compute+collective+host fractions sum to ~1.0 — and the ONLY
    extra host syncs vs a plain run are the probe's own fenced timings at
    the single attribution boundary (StepProbe.FETCHES_PER_MEASURE per
    timed variant); untouched steps pay zero."""
    from bpe_transformer_tpu.telemetry import validate_record
    from bpe_transformer_tpu.telemetry.attribution import StepProbe

    records_off, counts_off = _counting_attr_train(
        monkeypatch, byte_data, tmp_path, attribution_every=0
    )
    records_on, counts_on = _counting_attr_train(
        monkeypatch, byte_data, tmp_path, attribution_every=8
    )
    # One boundary (step 8), one single-device variant -> exactly
    # FETCHES_PER_MEASURE extra value fetches; no extra sync barriers.
    assert counts_on["device_get"] == (
        counts_off["device_get"] + StepProbe.FETCHES_PER_MEASURE
    )
    assert counts_on["block_until_ready"] == counts_off["block_until_ready"]

    attributions = [
        r for r in records_on if r.get("kind") == "attribution"
    ]
    assert [r["step"] for r in attributions] == [8]
    record = attributions[0]
    assert validate_record(record) == []
    total = (
        record["compute_frac"]
        + (record["collective_frac"] or 0.0)
        + record["host_gap_frac"]
    )
    assert total == pytest.approx(1.0, abs=0.02)
    assert record["device_step_s"] > 0
    # Single device: the collective split is exactly zero, not null.
    assert record["collective_frac"] == 0.0
    # The first record carries the static cost-model rows.
    programs = record["programs"]
    assert programs and programs[0]["name"] == "train_step"
    assert programs[0]["flops"] > 0
    assert programs[0]["bound"] in (
        "compute-bound", "memory-bound", "unknown"
    )
    # The probe's compile+measure time is spanned (and thus excluded from
    # the throughput window by the loop).
    assert any(
        r.get("kind") == "span" and r.get("name") == "attribution_probe"
        for r in records_on
    )
    # Flag off: no attribution records at all.
    assert not [r for r in records_off if r.get("kind") == "attribution"]


# ------------------------------- attribution: fixture, report, monitor, trace


def test_report_attribution_fixture_pins_section_and_compare(
    tmp_path, capsys
):
    """The committed attribution_tiny.jsonl pins the report's attribution
    section (step-time split, MFU ceiling, per-program roofline verdicts)
    and feeds the --compare gate: a stream whose collective_frac grew
    regresses with exit 3."""
    from bpe_transformer_tpu.telemetry.report import main as report_main

    fixture = str(FIXTURES / "attribution_tiny.jsonl")
    assert report_main([fixture]) == 0
    out = capsys.readouterr().out
    assert "== attribution (2 records, steps 50..100) ==" in out
    assert "compute 64.0%" in out
    assert "collective 10.5%" in out
    assert "host gap 25.5%" in out
    assert "mfu 0.13 -> 0.197 ceiling" in out
    assert "train_step" in out and "compute-bound" in out
    assert "decode_tick[8]" in out and "memory-bound" in out

    # Self-compare: shared metrics (incl. the new fraction gates), exit 0.
    assert report_main([fixture, "--compare", fixture]) == 0
    out = capsys.readouterr().out
    assert "collective_frac" in out and "host_gap_frac" in out
    assert "no regressions" in out

    # A stream whose collective fraction doubled: gate trips (exit 3).
    regressed = tmp_path / "attr_regressed.jsonl"
    regressed.write_text(
        Path(fixture).read_text()
        .replace('"collective_frac": 0.11', '"collective_frac": 0.3')
        .replace('"collective_frac": 0.1,', '"collective_frac": 0.28,')
    )
    assert report_main([str(regressed), "--compare", fixture]) == 3
    assert "collective_frac" in capsys.readouterr().out


def test_monitor_folds_attribution_into_live_state():
    from bpe_transformer_tpu.telemetry.monitor import (
        fold_records,
        render_frame,
    )

    records = load_records(FIXTURES / "attribution_tiny.jsonl")
    state = fold_records(records)
    assert state["compute_frac"] == 0.66  # latest record wins
    assert state["collective_frac"] == 0.1
    assert state["host_gap_frac"] == 0.24
    assert state["attribution_step"] == 100
    assert state["bound_verdict"] == "train_step compute-bound"
    frame = render_frame(state, "fixture")
    assert "attr" in frame
    assert "compute 66%" in frame
    assert "[train_step compute-bound]" in frame


def test_trace_attribution_counters_and_request_lanes(tmp_path):
    """The Chrome trace export grows an attribution counter track, and
    serving spans carrying a request_id land in per-request lanes (one
    queue->prefill->decode timeline per request)."""
    from bpe_transformer_tpu.telemetry.trace import trace_events

    events = trace_events(load_records(FIXTURES / "attribution_tiny.jsonl"))
    counters = [
        e for e in events if e.get("ph") == "C" and e["name"] == "attribution"
    ]
    assert len(counters) == 2
    assert counters[0]["args"]["compute_frac"] == 0.62
    assert counters[-1]["args"]["host_gap_frac"] == 0.24

    events = trace_events(load_records(FIXTURES / "serving_tiny.jsonl"))
    lanes = {
        e["args"]["name"]
        for e in events
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    }
    assert "request/req-a" in lanes and "request/req-b" in lanes
    # All three phases of req-a share its lane (a per-request timeline).
    tid_by_lane = {
        e["args"]["name"]: e["tid"]
        for e in events
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    }
    req_a_spans = [
        e
        for e in events
        if e.get("ph") == "X" and e.get("tid") == tid_by_lane["request/req-a"]
    ]
    assert {e["name"] for e in req_a_spans} == {
        "queue_wait", "prefill", "decode"
    }

    # Lane cap: a long serving stream must not explode into one Perfetto
    # row per request — beyond _MAX_REQUEST_LANES distinct ids the spans
    # fall back to the shared phase lanes.
    from bpe_transformer_tpu.telemetry.trace import _MAX_REQUEST_LANES

    many = [
        {"kind": "span", "name": "decode", "path": "serve/decode",
         "t": i * 0.01, "dur_s": 0.005, "request_id": f"req-{i:04d}"}
        for i in range(_MAX_REQUEST_LANES + 20)
    ]
    lanes = {
        e["args"]["name"]
        for e in trace_events(many)
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    }
    req_lanes = {l for l in lanes if l.startswith("request/")}
    assert len(req_lanes) == _MAX_REQUEST_LANES
    assert "serve/decode" in lanes  # overflow kept the shared phase lane


def test_report_serving_total_p99_and_dominant_phase(capsys):
    """The serving section attributes tail latency to a phase: total
    request p50/p95/p99 assembled from the request_id-tagged spans, with
    the slow tail's dominant phase named."""
    from bpe_transformer_tpu.telemetry.report import main as report_main

    records = load_records(FIXTURES / "serving_tiny.jsonl")
    serving = summarize(records)["serving"]
    assert serving["requests_traced"] == 3
    assert serving["total"]["p99_s"] is not None
    assert serving["slow_dominant_phase"] == "decode"
    assert serving["phases"]["decode"]["p99_s"] is not None

    assert report_main([str(FIXTURES / "serving_tiny.jsonl")]) == 0
    out = capsys.readouterr().out
    assert "slow tail dominated by decode" in out


@pytest.mark.slow
def test_profile_cli_smoke(tmp_path, capsys):
    """ACCEPTANCE (CPU degraded mode): bpe-tpu profile runs the cost model
    + measured split end to end on CPU, writes a schema-valid attribution
    stream, and the report renders its section."""
    from bpe_transformer_tpu.telemetry import validate_record
    from bpe_transformer_tpu.telemetry.report import main as report_main
    from bpe_transformer_tpu.training.cli import main as cli_main

    stream = tmp_path / "profile.jsonl"
    rc = cli_main(
        [
            "profile", "--preset", "ts-test", "--batch", "2",
            "--measure", "1", "--serve", "--slots", "2",
            "--metrics-jsonl", str(stream), "--json",
        ]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "== cost model" in out and "train_step" in out
    assert "prefill[16]" in out and "decode_tick[2]" in out
    assert "== measured split" in out

    records = load_records(stream)
    attribution = next(
        r for r in records if r.get("kind") == "attribution"
    )
    assert validate_record(attribution) == []
    total = (
        attribution["compute_frac"]
        + (attribution["collective_frac"] or 0.0)
        + attribution["host_gap_frac"]
    )
    assert total == pytest.approx(1.0, abs=0.02)
    # The stream is a real telemetry stream: manifest + footer + report.
    assert any(r.get("kind") == "manifest" for r in records)
    assert any(r.get("kind") == "footer" for r in records)
    assert report_main([str(stream)]) == 0
    assert "== attribution" in capsys.readouterr().out
