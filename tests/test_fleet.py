"""Fleet observability plane (ISSUE 12): anomaly-watchdog rules, SLO
burn-rate arithmetic, the fleet aggregator (canned replicas + router),
cross-stream request tracing, and the report/monitor surfaces.

Everything up to the E2E section is jax-free by construction — the
aggregator, SLO evaluator, alert rules, and monitor/report paths run on
front-end boxes with no accelerator runtime, and the tests pin that.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

import pytest

from bpe_transformer_tpu.telemetry.alerts import (
    AcceptRateCollapseRule,
    AlertEngine,
    BlockExhaustionRule,
    CompileStormRule,
    QueueGrowthRule,
    ReplicaFlapRule,
    default_fleet_rules,
    default_serving_rules,
)
from bpe_transformer_tpu.telemetry.fleet import (
    FleetAggregator,
    make_fleet_http_server,
    merge_histograms,
    parse_phase_histograms,
)
from bpe_transformer_tpu.telemetry.schema import validate_record
from bpe_transformer_tpu.telemetry.slo import (
    DEFAULT_OBJECTIVES,
    SLObjective,
    burn_summary,
    evaluate,
    hist_quantile,
    objectives_from_json,
)

REPO = Path(__file__).resolve().parent.parent
FIXTURE = REPO / "tests" / "fixtures" / "fleet_tiny.jsonl"
BASELINE = REPO / "tests" / "fixtures" / "slo_base_capture.json"


# ------------------------------------------------------------ alert rules


def test_queue_growth_rule_fires_and_clears():
    engine = AlertEngine([QueueGrowthRule(window=3, min_depth=3)])
    transitions = []
    for t, depth in enumerate([0, 1, 3, 6, 9, 2, 0]):
        transitions += engine.feed({"queue_depth": depth}, float(t))
    assert [r["state"] for r in transitions] == ["firing", "cleared"]
    firing, cleared = transitions
    assert firing["rule"] == "queue_growth" and firing["t"] == 2.0
    assert firing["queue_depth"] == 3 and firing["growth"] == 3
    assert "queue grew" in firing["message"]
    assert cleared["t"] == 5.0 and cleared["active_s"] == 3.0
    assert engine.active() == []
    for record in transitions:
        assert validate_record(record) == [], record


def test_queue_burst_that_drains_never_fires():
    """A momentary burst that shrinks inside the window is not sustained
    growth — the rule needs monotone non-decreasing depth across it."""
    engine = AlertEngine([QueueGrowthRule(window=3, min_depth=3)])
    transitions = []
    for t, depth in enumerate([0, 9, 4, 9, 3, 9, 2]):
        transitions += engine.feed({"queue_depth": depth}, float(t))
    assert transitions == []


def test_block_exhaustion_projects_time_to_dry():
    engine = AlertEngine([BlockExhaustionRule(window=3, horizon_s=30.0)])
    # Drain accelerates from -1 to -10 blocks/s: early windows project
    # hundreds of seconds to dry (no fire); at 278 free and -10/s the
    # projection crosses the 30s horizon and the rule fires once.
    out = []
    for t, free in enumerate([300, 299, 298, 288, 278, 268]):
        out += engine.feed({"kv_blocks_free": free}, float(t))
    assert len(out) == 1 and out[0]["state"] == "firing"
    assert out[0]["rule"] == "block_exhaustion"
    assert out[0]["projected_dry_s"] == pytest.approx(27.8, abs=0.1)
    # Pool refills (retirements freed blocks): slope flips, alert clears.
    out2 = engine.feed({"kv_blocks_free": 400}, 6.0)
    assert [r["state"] for r in out2] == ["cleared"]
    # Already-dry pool fires immediately, no trend needed.
    engine2 = AlertEngine([BlockExhaustionRule(window=4)])
    out3 = engine2.feed({"kv_blocks_free": 0}, 0.0)
    assert out3 and out3[0]["projected_dry_s"] == 0.0


def test_accept_collapse_and_compile_storm_rules():
    engine = AlertEngine(
        [
            AcceptRateCollapseRule(threshold=0.4, min_proposed=50),
            CompileStormRule(window=3, min_compiles=4),
        ]
    )
    # Too few proposals: rate 0.1 must NOT fire yet (cold-start guard).
    assert engine.feed(
        {"spec_accept_rate": 0.1, "spec_proposed": 10, "compile_events": 3},
        0.0,
    ) == []
    out = engine.feed(
        {"spec_accept_rate": 0.1, "spec_proposed": 100, "compile_events": 3},
        1.0,
    )
    assert [r["rule"] for r in out] == ["accept_rate_collapse"]
    # Compile counter jumps 5 inside the window: storm fires; recovery of
    # the accept rate clears the collapse in the same feed.
    out2 = engine.feed(
        {"spec_accept_rate": 0.8, "spec_proposed": 200, "compile_events": 8},
        2.0,
    )
    states = {r["rule"]: r["state"] for r in out2}
    assert states == {
        "accept_rate_collapse": "cleared", "compile_storm": "firing",
    }


def test_replica_flap_rule_counts_transitions_in_window():
    engine = AlertEngine([ReplicaFlapRule(window_s=100.0, max_transitions=3)])
    a_states = [True, False, True, False, True]  # 4 transitions: flapping
    out = []
    for t, up in enumerate(a_states):
        out += engine.feed(
            {"replica_online": {"http://a": up, "http://b": True}}, float(t)
        )
    assert len(out) == 1 and out[0]["state"] == "firing"
    assert out[0]["replica"] == "http://a" and out[0]["transitions"] >= 3
    # Edges age out of the window: the alert clears.
    out2 = engine.feed(
        {"replica_online": {"http://a": True, "http://b": True}}, 500.0
    )
    assert [r["state"] for r in out2] == ["cleared"]


def test_alert_engine_missing_data_keeps_state():
    """A sample with no evidence for a rule (dense replica without kv
    gauges) must neither fire nor clear it."""
    engine = AlertEngine([BlockExhaustionRule(window=3, horizon_s=1e9)])
    assert engine.feed({"kv_blocks_free": 100}, 0.0) == []
    assert engine.feed({"kv_blocks_free": 75}, 1.0) == []
    out = engine.feed({"kv_blocks_free": 50}, 2.0)
    assert [r["state"] for r in out] == ["firing"]
    # Evidence-free samples: the alert stays active.
    assert engine.feed({"queue_depth": 0}, 3.0) == []
    assert [a["rule"] for a in engine.active()] == ["block_exhaustion"]


def test_induced_queue_growth_and_block_exhaustion_incident():
    """ACCEPTANCE (watchdog): one incident trace — demand outruns the
    fleet (queue ramps) while the block pool drains — fires BOTH rules,
    and the recovery (queue drains, blocks freed) clears both."""
    engine = AlertEngine(default_fleet_rules())
    samples = [
        # t, queue, blocks_free  (64-block pool draining ~8/s)
        (0, 0, 60), (1, 2, 52), (2, 5, 44), (3, 9, 36), (4, 14, 28),
        # recovery: retirements free blocks, queue drains
        (5, 6, 50), (6, 1, 60), (7, 0, 62),
    ]
    log = []
    for t, queue, free in samples:
        log += engine.feed(
            {
                "queue_depth": queue,
                "kv_blocks_free": free,
                "kv_blocks_total": 64,
            },
            float(t),
        )
    fired = [r["rule"] for r in log if r["state"] == "firing"]
    cleared = [r["rule"] for r in log if r["state"] == "cleared"]
    assert set(fired) == {"queue_growth", "block_exhaustion"}
    assert set(cleared) == {"queue_growth", "block_exhaustion"}
    assert engine.active() == []
    for record in log:
        assert validate_record(record) == [], record


# ------------------------------------------------------------------- slo


def _fleet_record(t, ok, failed, hist=None, **extra):
    record = {
        "kind": "fleet", "t": float(t), "replicas_total": 2,
        "replicas_online": 2, "requests_ok": ok, "requests_failed": failed,
    }
    if hist is not None:
        record["hist_total"] = hist
    record.update(extra)
    return record


def test_slo_availability_burn_rate_window_delta():
    """Burn = (1-sli)/(1-target) over the WINDOW's counter delta, not the
    cumulative totals: early clean traffic must not dilute a fresh
    incident inside a short window."""
    records = [_fleet_record(t, 100 * (t + 1), 0) for t in range(5)]
    # Incident: 50 ok / 50 failed between t=4 and t=6.
    records.append(_fleet_record(6.0, 550, 50))
    objective = SLObjective(name="availability", target=0.99)
    short, long_w = evaluate(
        records, objectives=(objective,), windows_s=(3.0, 100.0)
    )
    assert short["window_s"] == 3.0
    assert short["good"] == 150 and short["total"] == 200
    assert short["burn_rate"] == pytest.approx((50 / 200) / 0.01)
    assert long_w["good"] == 550 and long_w["total"] == 600
    assert long_w["burn_rate"] == pytest.approx((50 / 600) / 0.01, rel=1e-3)
    for row in (short, long_w):
        assert validate_record(row) == [], row


def test_slo_latency_objective_counts_from_histogram():
    hist0 = [[0.5, 90], [2.5, 100], [None, 100]]
    hist1 = [[0.5, 91], [2.5, 200], [None, 220]]
    records = [
        _fleet_record(0.0, 0, 0, hist=hist0),
        _fleet_record(10.0, 0, 0, hist=hist1),
    ]
    objective = SLObjective(
        name="lat", target=0.9, phase="total", threshold_s=0.5
    )
    (row,) = evaluate(records, objectives=(objective,), windows_s=(5.0,))
    # Window covers only the second record: delta good=1, total=120.
    assert row["good"] == 1 and row["total"] == 120
    assert row["sli"] == pytest.approx(1 / 120, abs=1e-6)
    assert row["threshold_s"] == 0.5
    # Off-edge thresholds round DOWN (strict): a 0.7s objective judges
    # from the 0.5 bucket — a 0.6s request cannot be PROVEN good from
    # the histogram, so it counts bad; the SLI is only ever understated.
    objective2 = SLObjective(
        name="lat2", target=0.9, phase="total", threshold_s=0.7
    )
    (row2,) = evaluate(records, objectives=(objective2,), windows_s=(100.0,))
    assert row2["good"] == 91 and row2["total"] == 220


def test_slo_tolerates_counter_dips_from_replica_dropout():
    """A merged cumulative counter DIPS when a replica dies or restarts
    mid-window — exactly the incident an SLO must measure.  Window counts
    are per-step clamped increase sums (Prometheus increase() form), so
    the surviving replica's traffic still scores instead of the window
    reading 'no traffic' off a negative raw delta."""
    objective = SLObjective(name="availability", target=0.99)
    records = [
        _fleet_record(0.0, 100, 0),
        _fleet_record(1.0, 200, 0),
        # Replica carrying half the history dies: merged counters dip.
        _fleet_record(2.0, 110, 5),
        # Survivor keeps serving (5 more failures land during failover).
        _fleet_record(3.0, 150, 10),
    ]
    (row,) = evaluate(records, objectives=(objective,), windows_s=(2.5,))
    # Steps inside the window: (100,100)->(200,200) = +100/+100; the dip
    # to (110,115) clamps to 0/0; (110,115)->(150,160) = +40/+45.
    assert row["good"] == 140 and row["total"] == 145
    assert row["burn_rate"] == pytest.approx((5 / 145) / 0.01, rel=1e-3)


def test_fleet_keeps_offline_replicas_last_histograms():
    """The requests a dead replica already served HAPPENED: its last-known
    cumulative buckets stay in the merge, so the fleet latency counters
    never dip on a replica death (the SLO clamp is the backstop for real
    counter RESETS, not the primary path)."""
    a = _FakeServeReplica(
        hist_total=[[0.5, 10], [None, 10]],
    )
    b = _FakeServeReplica(
        hist_total=[[0.5, 7], [None, 7]],
    )
    fleet = FleetAggregator([a.url, b.url], poll_timeout_s=1.0)
    try:
        first = fleet.poll_once()
        assert first["hist_total"] == [[0.5, 17], [None, 17]]
        a.close()
        b.state["hist_total"] = [[0.5, 9], [None, 9]]
        second = fleet.poll_once()
        assert second["replicas_online"] == 1
        # A's 10 served requests survive its death in the merge.
        assert second["hist_total"] == [[0.5, 19], [None, 19]]
    finally:
        b.close()
        try:
            a.close()
        except Exception:  # noqa: BLE001 — already closed above
            pass


def test_slo_no_traffic_reports_null_burn():
    records = [_fleet_record(t, 100, 0) for t in range(3)]
    (row,) = evaluate(
        records,
        objectives=(SLObjective(name="availability", target=0.99),),
        windows_s=(1.5,),
    )
    assert row["total"] == 0 and row["burn_rate"] is None
    assert validate_record(row) == []


def test_objectives_from_json_validates():
    parsed = objectives_from_json(
        '[{"name": "availability", "target": 0.999},'
        ' {"name": "p99", "target": 0.99, "phase": "total",'
        ' "threshold_s": 2.5}]'
    )
    assert [o.name for o in parsed] == ["availability", "p99"]
    with pytest.raises(ValueError, match="not valid JSON"):
        objectives_from_json("{")
    with pytest.raises(ValueError, match="non-empty JSON list"):
        objectives_from_json("[]")
    with pytest.raises(ValueError, match="unknown keys"):
        objectives_from_json('[{"name": "x", "target": 0.9, "oops": 1}]')
    with pytest.raises(ValueError, match="come together"):
        objectives_from_json('[{"name": "x", "target": 0.9, "phase": "total"}]')
    with pytest.raises(ValueError, match="target must be in"):
        objectives_from_json('[{"name": "x", "target": 2}]')


def test_histogram_merge_and_quantile():
    merged = merge_histograms(
        [
            [[0.5, 10], [2.5, 20], [None, 20]],
            [[0.5, 5], [2.5, 5], [None, 6]],
        ]
    )
    assert merged == [[0.5, 15], [2.5, 25], [None, 26]]
    assert hist_quantile(merged, 0.5) == 0.5
    assert hist_quantile(merged, 0.99) == 2.5
    assert hist_quantile([], 0.5) is None
    text = (
        'bpe_tpu_request_phase_seconds_bucket{phase="total",le="0.5"} 3\n'
        'bpe_tpu_request_phase_seconds_bucket{phase="total",le="+Inf"} 4\n'
        'bpe_tpu_request_phase_seconds_bucket{phase="ttfb",le="0.25"} 4\n'
        "bpe_tpu_other_metric 7\n"
    )
    hists = parse_phase_histograms(text)
    assert hists["total"] == [[0.5, 3], [None, 4]]
    assert hists["ttfb"] == [[0.25, 4]]


# --------------------------------------------------- aggregator (canned)


class _FakeServeReplica:
    """A canned replica: /statusz JSON + /metrics exposition, mutable
    between sweeps so the aggregator's rate/trend logic is testable."""

    def __init__(self, *, slots=2, queue=0, active=0, kv_free=None,
                 kv_total=None, draining=False, tokens=0.0,
                 hist_total=None, hist_ttfb=None, alerts=None):
        self.state = {
            "slots": slots, "queue": queue, "active": active,
            "kv_free": kv_free, "kv_total": kv_total,
            "draining": draining, "tokens": tokens,
            "hist_total": hist_total or [], "hist_ttfb": hist_ttfb or [],
            "alerts": alerts or [],
        }
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def do_GET(self):
                state = outer.state
                if self.path == "/statusz":
                    page = {
                        "worker_alive": True,
                        "draining": state["draining"],
                        "engine_kind": "paged",
                        "queue_depth": state["queue"],
                        "slots": state["slots"],
                        "active_slots": state["active"],
                        "requests_finished": 5,
                        "alerts": state["alerts"],
                    }
                    if state["kv_total"] is not None:
                        page["kvpool"] = {
                            "kv_blocks_free": state["kv_free"],
                            "kv_blocks_total": state["kv_total"],
                        }
                    body = json.dumps(page).encode()
                    ctype = "application/json"
                elif self.path == "/metrics":
                    lines = [
                        f"bpe_tpu_tokens_generated_total {state['tokens']}",
                        "bpe_tpu_compile_events_total 7",
                    ]
                    for phase, hist in (
                        ("total", state["hist_total"]),
                        ("ttfb", state["hist_ttfb"]),
                    ):
                        for le, count in hist:
                            le_text = "+Inf" if le is None else f"{le:g}"
                            lines.append(
                                "bpe_tpu_request_phase_seconds_bucket"
                                f'{{phase="{phase}",le="{le_text}"}} {count}'
                            )
                    body = "\n".join(lines).encode()
                    ctype = "text/plain; version=0.0.4"
                else:
                    body, ctype = b"{}", "application/json"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.url = f"http://127.0.0.1:{self.server.server_address[1]}"
        self.thread = threading.Thread(
            target=self.server.serve_forever, daemon=True
        )
        self.thread.start()

    def close(self):
        self.server.shutdown()
        self.server.server_close()
        self.thread.join(timeout=10)


class _FakeRouter:
    def __init__(self, routed=100, failed=0):
        self.routed, self.failed = routed, failed
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def do_GET(self):
                body = json.dumps(
                    {
                        "requests_routed": outer.routed,
                        "requests_failed": outer.failed,
                        "requests_retried": 0,
                    }
                ).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.url = f"http://127.0.0.1:{self.server.server_address[1]}"
        self.thread = threading.Thread(
            target=self.server.serve_forever, daemon=True
        )
        self.thread.start()

    def close(self):
        self.server.shutdown()
        self.server.server_close()
        self.thread.join(timeout=10)


def test_fleet_sweep_merges_replicas_router_and_histograms():
    """ACCEPTANCE (aggregator): one sweep folds statusz occupancy,
    /metrics counters, worst-replica KV headroom, router availability,
    and EXACTLY-merged latency histograms into one schema-valid
    kind=fleet record; a second sweep derives token rates from the
    cumulative counters."""
    a = _FakeServeReplica(
        slots=2, queue=1, active=2, kv_free=8, kv_total=32, tokens=100,
        hist_total=[[0.5, 10], [2.5, 10], [None, 10]],
        hist_ttfb=[[0.25, 10], [None, 10]],
    )
    b = _FakeServeReplica(
        slots=2, queue=0, active=1, kv_free=24, kv_total=32, tokens=50,
        draining=True,
        hist_total=[[0.5, 5], [2.5, 9], [None, 10]],
        hist_ttfb=[[0.25, 2], [None, 10]],
        alerts=[{"rule": "queue_growth"}],
    )
    router = _FakeRouter(routed=99, failed=1)
    try:
        fleet = FleetAggregator(
            [a.url, b.url], router_url=router.url, poll_timeout_s=5.0
        )
        record = fleet.poll_once()
        assert validate_record(record) == [], record
        assert record["replicas_total"] == 2
        assert record["replicas_online"] == 2
        assert record["replicas_draining"] == 1
        assert record["queue_depth"] == 1 and record["active_slots"] == 3
        assert record["kv_blocks_free"] == 32
        assert record["kv_headroom_frac"] == pytest.approx(8 / 32)
        assert record["requests_ok"] == 99 and record["requests_failed"] == 1
        assert record["availability"] == pytest.approx(0.99)
        assert record["hist_total"] == [[0.5, 15], [2.5, 19], [None, 20]]
        # Merged p99: rank 20 of 20 -> the 2.5 bucket; per-replica p99s
        # averaged would have said 0.5 and 2.5 — the merge is the truth.
        assert record["request_p99_s"] == 2.5
        assert record["ttfb_p99_s"] == 0.25
        by_url = {r["url"]: r for r in record["per_replica"]}
        assert by_url[b.url]["alerts_firing"] == 1
        assert record["tokens_per_sec"] is None  # no previous sweep yet

        a.state["tokens"] = 300.0
        b.state["tokens"] = 150.0
        time.sleep(0.05)
        record2 = fleet.poll_once()
        assert record2["tokens_per_sec"] is not None
        assert record2["tokens_per_sec"] > 0
        by_url2 = {r["url"]: r for r in record2["per_replica"]}
        assert by_url2[a.url]["tokens_per_sec"] > by_url2[b.url][
            "tokens_per_sec"
        ]
    finally:
        a.close()
        b.close()
        router.close()


def test_fleet_dead_host_marks_offline_without_stalling():
    """PR-8 poller discipline: a dead replica costs ONE poll timeout and
    is reported offline; the live replica's data still lands."""
    live = _FakeServeReplica(slots=2, active=1)
    try:
        fleet = FleetAggregator(
            [live.url, "http://127.0.0.1:9"], poll_timeout_s=1.0
        )
        t0 = time.monotonic()
        record = fleet.poll_once()
        assert time.monotonic() - t0 < 5.0
        assert record["replicas_online"] == 1
        dead = next(
            r for r in record["per_replica"]
            if r["url"] == "http://127.0.0.1:9"
        )
        assert not dead["online"] and "poll failed" in dead["error"]
    finally:
        live.close()


def test_fleet_emits_slo_and_alert_records_through_telemetry():
    """Sweeps write kind=fleet + kind=slo rows each poll, and the fleet
    alert rules (here: queue growth across sweeps) fire/clear through the
    same stream — every record schema-valid."""
    replica = _FakeServeReplica(slots=2, queue=0)
    router = _FakeRouter(routed=10, failed=0)
    records = []

    class _Sink:
        def emit(self, record):
            records.append(record)

    try:
        fleet = FleetAggregator(
            [replica.url],
            router_url=router.url,
            telemetry=_Sink(),
            alert_rules=[QueueGrowthRule(window=2, min_depth=2)],
            slo_windows_s=(60.0,),
        )
        for queue in (0, 2, 5, 0):
            replica.state["queue"] = queue
            fleet.poll_once()
        kinds = [r.get("kind") for r in records]
        assert kinds.count("fleet") == 4
        assert kinds.count("slo") == 4 * len(DEFAULT_OBJECTIVES)
        alert_states = [
            r["state"] for r in records if r.get("kind") == "alert"
        ]
        assert alert_states == ["firing", "cleared"]
        for record in records:
            assert validate_record(record) == [], record
        # The availability objective saw router counters: sli == 1.0.
        avail = [
            r for r in records
            if r.get("kind") == "slo" and r["objective"] == "availability"
        ]
        assert avail[-1]["sli"] == 1.0 and avail[-1]["burn_rate"] == 0.0
        # statusz mirrors the stream.
        page = fleet.statusz()
        assert page["fleet"]["replicas_online"] == 1
        assert page["alerts"] == []  # cleared by the last sweep
        assert len(page["slo"]) == len(DEFAULT_OBJECTIVES)
    finally:
        replica.close()
        router.close()


def test_fleet_http_surface_statusz_and_metrics():
    replica = _FakeServeReplica(slots=2, active=1, kv_free=4, kv_total=32)
    try:
        fleet = FleetAggregator([replica.url])
        fleet.poll_once()
        server = make_fleet_http_server(fleet, port=0)
        port = server.server_address[1]
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        try:
            base = f"http://127.0.0.1:{port}"
            page = json.loads(
                urllib.request.urlopen(f"{base}/statusz", timeout=30).read()
            )
            assert page["fleet"]["replicas_online"] == 1
            assert page["replicas"][0]["url"] == replica.url
            health = json.loads(
                urllib.request.urlopen(f"{base}/healthz", timeout=30).read()
            )
            assert health["ok"]
            prom = urllib.request.urlopen(
                f"{base}/metrics", timeout=30
            ).read().decode()
            assert "bpe_tpu_fleet_replicas_online 1" in prom
            assert "bpe_tpu_fleet_kv_headroom_frac 0.125" in prom
            assert 'bpe_tpu_fleet_replica_online{replica="' in prom
            assert "bpe_tpu_fleet_slo_burn_rate" in prom
        finally:
            server.shutdown()
            server.server_close()
            thread.join(timeout=10)
    finally:
        replica.close()


def test_fleet_and_monitor_jax_free():
    """ACCEPTANCE: the fleet/slo/alert/monitor paths import and run with
    jax made unimportable — pinned like the router and monitor."""
    script = (
        "import sys\n"
        "sys.modules['jax'] = None\n"
        "from bpe_transformer_tpu.telemetry.fleet import FleetAggregator\n"
        "from bpe_transformer_tpu.telemetry.slo import evaluate\n"
        "from bpe_transformer_tpu.telemetry.alerts import AlertEngine, "
        "default_serving_rules, default_fleet_rules\n"
        "from bpe_transformer_tpu.telemetry.monitor import FleetSource, "
        "fold_records, render_frame\n"
        "from bpe_transformer_tpu.telemetry.trace import request_timeline\n"
        "fleet = FleetAggregator(['http://127.0.0.1:9'], "
        "poll_timeout_s=0.5)\n"
        "record = fleet.poll_once()\n"
        "assert record['replicas_online'] == 0\n"
        "assert fleet.statusz()['fleet']['replicas_total'] == 1\n"
        "assert 'bpe_tpu_fleet_replicas_online 0' in "
        "fleet.prometheus_metrics()\n"
        "state = fold_records([record])\n"
        "assert state['fleet_replicas_total'] == 1\n"
        "render_frame(state, 'test')\n"
        "print('ok')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "PYTHONPATH": str(REPO)},
        cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert proc.stdout.strip() == "ok"


def test_fleet_cli_once_mode():
    """`bpe-tpu fleet --once`: one sweep, the record on stdout, exit 0 —
    scriptable like monitor --once (and jax-free through the real CLI)."""
    proc = subprocess.run(
        [
            sys.executable, "-m", "bpe_transformer_tpu.training.cli",
            "fleet", "--replica", "http://127.0.0.1:9",
            "--poll-timeout", "0.5", "--once",
        ],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "PYTHONPATH": str(REPO), "JAX_PLATFORMS": "cpu"},
        cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    record = json.loads(proc.stdout.strip().splitlines()[-1])
    assert record["kind"] == "fleet" and record["replicas_online"] == 0


def test_fleet_cli_rejects_bad_slo_config():
    proc = subprocess.run(
        [
            sys.executable, "-m", "bpe_transformer_tpu.training.cli",
            "fleet", "--replica", "http://127.0.0.1:9",
            "--slo-config", '[{"name": "x"}]', "--once",
        ],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "PYTHONPATH": str(REPO), "JAX_PLATFORMS": "cpu"},
        cwd=str(REPO),
    )
    assert proc.returncode == 2
    assert "bad --slo-config" in proc.stderr


# ------------------------------------------------- report/monitor pins


def test_report_fleet_fixture_sections_pinned():
    from bpe_transformer_tpu.telemetry.report import (
        load_records,
        render_report,
        summarize,
    )

    records = load_records(FIXTURE)
    summary = summarize(records)
    assert summary["fleet"]["n"] == 3
    assert summary["fleet"]["replicas_total"] == 2
    assert summary["fleet"]["kv_headroom_frac"]["min"] == pytest.approx(
        0.3125
    )
    assert summary["slo"]["max_burn_rate"] == 40.0
    assert summary["alerts"]["fired"] == 2
    assert summary["alerts"]["firing_at_end"] == ["block_exhaustion"]
    text = render_report(records)
    assert "== fleet (3 sweeps) ==" in text
    assert "== slo (5 evaluations) ==" in text
    assert "BURNING ERROR BUDGET" in text
    assert "== alerts (2 fired, 1 still firing) ==" in text
    assert "alert queue_growth fired" in text
    assert "alerts still firing at stream end: block_exhaustion" in text


def test_report_baseline_gates_slo_burn_regression(capsys):
    """ACCEPTANCE: `report --baseline` exits 3 when the stream's worst
    SLO burn rate regresses past the pinned capture baseline — a serving
    SLO regression fails CI exactly like a throughput regression."""
    from bpe_transformer_tpu.telemetry.report import main as report_main

    rc = report_main([str(FIXTURE), "--baseline", str(BASELINE)])
    out = capsys.readouterr().out
    assert rc == 3
    assert "slo_max_burn_rate" in out and "regressed" in out
    assert "fleet_request_p99_s" in out


def test_report_slo_flag_graceful_without_fleet_records(capsys, tmp_path):
    """Satellite: --slo on a stream with no fleet/slo records prints a
    notice and exits 0 (PR-3 graceful-empty precedent), and on a
    fleet-records-only stream evaluates the default objectives."""
    from bpe_transformer_tpu.telemetry.report import (
        load_records,
        main as report_main,
    )

    rc = report_main(
        [str(REPO / "tests" / "fixtures" / "telemetry_tiny.jsonl"), "--slo"]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "no fleet/slo records in this stream" in out

    # Fleet records only (slo rows stripped): --slo evaluates on demand.
    fleet_only = tmp_path / "fleet_only.jsonl"
    with open(fleet_only, "w") as f:
        for record in load_records(FIXTURE):
            if record.get("kind") in ("fleet", "manifest"):
                f.write(json.dumps(record) + "\n")
    rc = report_main([str(fleet_only), "--slo"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "== slo (" in out and "availability" in out


def test_monitor_folds_fleet_slo_alert_records():
    from bpe_transformer_tpu.telemetry.monitor import (
        fold_records,
        render_frame,
    )
    from bpe_transformer_tpu.telemetry.report import load_records

    state = fold_records(load_records(FIXTURE))
    assert state["fleet_replicas_online"] == 1
    assert state["fleet_replicas_total"] == 2
    assert state["slo_max_burn"] == 40.0
    # queue_growth cleared; block_exhaustion still firing.
    assert state["alerts_firing"] == ["block_exhaustion"]
    frame = render_frame(state, "fixture")
    assert "fleet  replicas 1/2" in frame
    assert "burn 40" in frame
    assert "FIRING: block_exhaustion" in frame


def test_monitor_fleet_source_polls_aggregator_statusz():
    replica = _FakeServeReplica(slots=2, active=1, kv_free=16, kv_total=32)
    try:
        fleet = FleetAggregator([replica.url], slo_windows_s=(60.0,))
        fleet.poll_once()
        server = make_fleet_http_server(fleet, port=0)
        port = server.server_address[1]
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        try:
            from bpe_transformer_tpu.telemetry.monitor import (
                FleetSource,
                render_frame,
            )

            source = FleetSource(f"127.0.0.1:{port}")
            state = source.refresh()
            assert state["fleet_replicas_online"] == 1
            assert state["fleet_kv_headroom_frac"] == 0.5
            frame = render_frame(state, source.label)
            assert "fleet  replicas 1/1" in frame
        finally:
            server.shutdown()
            server.server_close()
            thread.join(timeout=10)
    finally:
        replica.close()


# ------------------------------------------------------ request tracing


def _span(path, t, dur, rid, wall, **attrs):
    return {
        "kind": "span", "name": path.split("/")[-1], "path": path,
        "t": t, "dur_s": dur, "request_id": rid, "time_unix": wall,
        **attrs,
    }


def test_request_timeline_joins_router_and_replica_streams():
    """ACCEPTANCE (tracing, stream shape): one trace_id assembles the
    router's hop spans and the replica's phase spans — from two streams
    with DIFFERENT t epochs — into one wall-clock-ordered timeline, the
    failover case showing both attempted hops."""
    rid = "trace-e2e-1"
    wall = 1_785_758_000.0
    router_stream = [
        {"kind": "manifest", "run_kind": "route", "time_utc": "x",
         "host": "front"},
        _span("router/pick", 5.0, 0.001, rid, wall, n_available=2),
        _span("router/hop", 5.002, 0.02, rid, wall + 0.002,
              replica="http://a", hop=0, outcome="connect_failed"),
        _span("router/hop", 5.03, 0.4, rid, wall + 0.03,
              replica="http://b", hop=1, outcome="ok", ttfb_s=0.39),
        _span("router/request", 5.0, 0.45, rid, wall, status=200, hops=2),
        _span("router/hop", 9.0, 0.1, "other-trace", wall + 9.0,
              replica="http://b", hop=0, outcome="ok"),
    ]
    # The replica's own epoch started much earlier: its t values are
    # large, but time_unix places its spans inside the router's hop.
    replica_stream = [
        _span("serve/queue_wait", 100.0, 0.01, rid, wall + 0.04),
        _span("serve/prefill", 100.01, 0.05, rid, wall + 0.05),
        _span("serve/decode", 100.06, 0.3, rid, wall + 0.1),
    ]
    from bpe_transformer_tpu.telemetry.trace import (
        request_timeline,
        trace_events,
    )

    rows = request_timeline([router_stream, replica_stream], rid)
    assert [r["path"] for r in rows] == [
        "router/pick", "router/request", "router/hop", "router/hop",
        "serve/queue_wait", "serve/prefill", "serve/decode",
    ]
    hops = [r for r in rows if r["path"] == "router/hop"]
    assert [h["outcome"] for h in hops] == ["connect_failed", "ok"]
    assert all(r["stream"] == 0 for r in rows[:4])
    assert all(r["stream"] == 1 for r in rows[4:])
    assert rows[0]["t_rel"] == 0.0
    rels = [r["t_rel"] for r in rows]
    assert rels == sorted(rels)
    # Other requests never leak into the timeline.
    assert all(r["request_id"] == rid for r in rows)

    # Chrome export: router spans with a request_id land in the same
    # request/<id> lane the serve spans use.
    events = trace_events(router_stream + replica_stream)
    lanes = {
        e["args"]["name"]: e["tid"]
        for e in events
        if e.get("name") == "thread_name"
    }
    assert f"request/{rid}" in lanes
    lane = lanes[f"request/{rid}"]
    in_lane = [
        e for e in events if e.get("ph") == "X" and e["tid"] == lane
    ]
    assert len(in_lane) == 7


# ------------------------------------------------------ tier-1 budget


def test_tier1_budget_collect_gate():
    """Satellite: the PR-11 budget guard GATES commits — tier-1 runs it
    in --collect mode, so a pile of unmarked heavy tests fails here
    before the driver's 870s kill ever fires."""
    proc = subprocess.run(
        [
            sys.executable,
            str(REPO / "tools" / "check_tier1_budget.py"),
            "--collect",
        ],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        cwd=str(REPO),
    )
    assert proc.returncode == 0, (
        proc.stdout[-2000:] + proc.stderr[-2000:]
    )
    assert "within ceiling" in proc.stdout


# ------------------------------------------------------------------- e2e


@pytest.mark.slow
@pytest.mark.serving
def test_fleet_observability_e2e_two_paged_replicas(tmp_path):
    """ACCEPTANCE: two in-process paged replicas behind the REAL router,
    each narrating its own JSONL — one trace_id assembles the full
    router -> replica -> engine timeline across the streams (the
    failover case shows BOTH attempted hops), and the fleet aggregator
    folds the live fleet (one replica down) into schema-valid records."""
    import dataclasses

    import jax

    from bpe_transformer_tpu.models import TS_TEST_CONFIG, init_params
    from bpe_transformer_tpu.serving import ServingEngine, make_http_server
    from bpe_transformer_tpu.serving.router import (
        Router,
        make_router_http_server,
    )
    from bpe_transformer_tpu.telemetry import MetricsLogger, Telemetry
    from bpe_transformer_tpu.telemetry.report import load_records
    from bpe_transformer_tpu.telemetry.trace import request_timeline

    cfg = dataclasses.replace(
        TS_TEST_CONFIG, vocab_size=128, context_length=32
    )
    params = init_params(jax.random.PRNGKey(0), cfg)

    def start_replica(name):
        logger = MetricsLogger(jsonl_path=tmp_path / f"{name}.jsonl")
        telemetry = Telemetry(sink=logger.log)
        serving = ServingEngine(
            params, cfg, slots=2, min_bucket=8, paged=True, block_size=8,
            telemetry=telemetry, engine_record_every_s=0.2,
        )
        serving.start()
        server = make_http_server(serving, port=0)
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        return {
            "serving": serving, "server": server, "thread": thread,
            "logger": logger, "port": server.server_address[1],
            "stream": tmp_path / f"{name}.jsonl",
        }

    a = start_replica("replica_a")
    b = start_replica("replica_b")
    url_a = f"http://127.0.0.1:{a['port']}"
    url_b = f"http://127.0.0.1:{b['port']}"
    router_stream = tmp_path / "router.jsonl"
    router_logger = MetricsLogger(jsonl_path=router_stream)
    router = Router(
        [url_a, url_b], poll_interval_s=0.2,
        telemetry=Telemetry(sink=router_logger.log),
    ).start()
    rserver = make_router_http_server(router, port=0)
    rthread = threading.Thread(target=rserver.serve_forever, daemon=True)
    rthread.start()
    rport = rserver.server_address[1]

    try:
        # Happy path through the real HTTP front: client-supplied trace
        # id, echoed end to end.
        req = urllib.request.Request(
            f"http://127.0.0.1:{rport}/generate",
            data=json.dumps(
                {"prompt_ids": [3, 5, 7, 9], "max_new_tokens": 4,
                 "temperature": 0.0}
            ).encode(),
            headers={"Content-Type": "application/json",
                     "X-Request-Id": "e2e-happy"},
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            assert resp.headers["X-Request-Id"] == "e2e-happy"
            out = json.loads(resp.read())
        assert out["request_id"] == "e2e-happy"
        served_url = out["replica"]

        # Failover: kill replica A's HTTP front (engine still alive —
        # a network death, the router's connect-failure path), stop the
        # poller, and force A first in weight order so the request MUST
        # burn a hop on it before winning on B.
        router.close()  # deterministic: no poll races the assertion
        a["server"].shutdown()
        a["server"].server_close()
        a["thread"].join(timeout=10)
        state = {r.url: r for r in router.replicas}
        for r in router.replicas:
            r.healthy, r.draining = True, False
        state[url_a].slots, state[url_a].active_slots = 8, 0
        state[url_b].slots, state[url_b].active_slots = 1, 0
        code, payload = router.handle_generate(
            json.dumps(
                {"prompt_ids": [2, 4, 6], "max_new_tokens": 3,
                 "temperature": 0.0}
            ).encode(),
            trace_id="e2e-failover",
        )
        assert code == 200 and payload["replica"] == url_b
        assert payload["request_id"] == "e2e-failover"

        # Cross-stream assembly: one trace_id stitches the router's
        # hops and the replica's engine-phase spans into one timeline.
        streams = [
            load_records(router_stream),
            load_records(a["stream"]),
            load_records(b["stream"]),
        ]
        rows = request_timeline(streams, "e2e-failover")
        hops = [r for r in rows if r["path"] == "router/hop"]
        assert [h["outcome"] for h in hops] == ["connect_failed", "ok"]
        assert [h["replica"] for h in hops] == [url_a, url_b]
        serve_paths = [
            r["path"] for r in rows if r["path"].startswith("serve/")
        ]
        assert serve_paths == [
            "serve/queue_wait", "serve/prefill", "serve/decode"
        ]
        assert all(r["stream"] == 2 for r in rows
                   if r["path"].startswith("serve/"))
        rels = [r["t_rel"] for r in rows if r["t_rel"] is not None]
        assert rels == sorted(rels) and rels[0] == 0.0
        # The happy request traces too (single ok hop on its replica).
        happy = request_timeline(streams, "e2e-happy")
        happy_hops = [r for r in happy if r["path"] == "router/hop"]
        assert [h["outcome"] for h in happy_hops] == ["ok"]
        assert happy_hops[0]["replica"] == served_url
        assert any(r["path"] == "serve/decode" for r in happy)

        # Fleet aggregator over the live fleet: A's front is dead, B is
        # serving — the sweep marks one online, merges B's histograms
        # (the ttfb/total evidence the requests above produced), and
        # every emitted record validates.
        fleet_records = []

        class _Sink:
            def emit(self, record):
                fleet_records.append(record)

        fleet = FleetAggregator(
            [url_a, url_b], poll_timeout_s=2.0, telemetry=_Sink(),
            slo_windows_s=(60.0,),
        )
        record = fleet.poll_once()
        assert record["replicas_online"] == 1
        assert record["hist_total"] and record["hist_ttfb"]
        assert record["request_p99_s"] is not None
        by_url = {r["url"]: r for r in record["per_replica"]}
        assert not by_url[url_a]["online"]
        assert by_url[url_b]["engine_kind"] == "paged"
        assert by_url[url_b]["kv_blocks_total"] > 0
        for emitted in fleet_records:
            assert validate_record(emitted) == [], emitted
    finally:
        rserver.shutdown()
        rserver.server_close()
        rthread.join(timeout=10)
        router.close()
        b["server"].shutdown()
        b["server"].server_close()
        b["thread"].join(timeout=10)
        for replica in (a, b):
            replica["serving"].close()
            replica["logger"].close()
        router_logger.close()


def test_burn_summary_keeps_windows_separate():
    """Regression: the 5-minute burn paging while the 1-hour burn shrugs
    is the whole point of multi-window evaluation — the digest must not
    overwrite the short window's spike with the long window's calm."""
    rows = [
        {"kind": "slo", "t": 1.0, "objective": "availability",
         "window_s": 300.0, "target": 0.999, "sli": 0.986,
         "burn_rate": 14.0},
        {"kind": "slo", "t": 1.0, "objective": "availability",
         "window_s": 3600.0, "target": 0.999, "sli": 0.9996,
         "burn_rate": 0.4},
    ]
    digest = burn_summary(rows)
    short = digest["objectives"]["availability (300s)"]
    long_w = digest["objectives"]["availability (3600s)"]
    assert short["last_burn"] == 14.0 and short["window_s"] == 300.0
    assert long_w["last_burn"] == 0.4 and long_w["window_s"] == 3600.0
    assert digest["max_burn_rate"] == 14.0


def test_report_slo_on_demand_feeds_baseline_gate(capsys, tmp_path):
    """Regression: `--slo --baseline` on a fleet-records-only stream must
    GATE the on-demand burn, not just print it — exit 3 when the
    evaluated worst burn regresses past the pinned capture."""
    from bpe_transformer_tpu.telemetry.report import (
        load_records,
        main as report_main,
    )

    fleet_only = tmp_path / "fleet_only.jsonl"
    with open(fleet_only, "w") as f:
        for record in load_records(FIXTURE):
            if record.get("kind") in ("fleet", "manifest"):
                f.write(json.dumps(record) + "\n")
    rc = report_main(
        [str(fleet_only), "--slo", "--baseline", str(BASELINE)]
    )
    out = capsys.readouterr().out
    assert rc == 3
    assert "slo_max_burn_rate" in out and "regressed" in out
