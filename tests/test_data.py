"""Batch sampler statistics + memmap tokenize/load pipeline."""

import math
from collections import Counter

import numpy as np
import pytest

from bpe_transformer_tpu.data import (
    BatchLoader,
    get_batch,
    load_token_file,
    tokenize_to_memmap,
)


def test_get_batch_shapes_shift_and_uniformity():
    """Reference contract (`test_data.py:10-72`): shapes, y = x+1 shift, and
    uniform start indices within ±5 sigma over 1000 draws."""
    dataset = np.arange(0, 100)
    context_length = 7
    batch_size = 32
    rng = np.random.default_rng(1234)

    starting = Counter()
    num_iters = 1000
    for _ in range(num_iters):
        x, y = get_batch(dataset, batch_size, context_length, rng)
        assert x.shape == (batch_size, context_length)
        assert y.shape == (batch_size, context_length)
        np.testing.assert_array_equal(x + 1, y)
        starting.update(x[:, 0].tolist())

    n_starts = len(dataset) - context_length
    assert max(starting) == n_starts - 1
    assert min(starting) == 0
    expected = num_iters * batch_size / n_starts
    sigma = math.sqrt(
        num_iters * batch_size * (1 / n_starts) * (1 - 1 / n_starts)
    )
    for idx, count in starting.items():
        assert expected - 5 * sigma < count < expected + 5 * sigma, idx


def test_get_batch_rejects_short_dataset():
    with pytest.raises(ValueError):
        get_batch(np.arange(5), batch_size=2, context_length=10)


def test_batch_loader_deterministic_with_seed():
    data = np.arange(1000)
    a = BatchLoader(data, 4, 16, seed=7)
    b = BatchLoader(data, 4, 16, seed=7)
    xa, ya = next(a)
    xb, yb = next(b)
    np.testing.assert_array_equal(xa, xb)
    np.testing.assert_array_equal(ya, yb)


def test_tokenize_to_memmap_roundtrip(tmp_path, tiny_corpus):
    from bpe_transformer_tpu.tokenization import BPETokenizer, train_bpe

    vocab, merges = train_bpe(tiny_corpus, 300, ["<|endoftext|>"])
    tok = BPETokenizer(vocab, merges, ["<|endoftext|>"])

    out = tmp_path / "tokens.bin"
    mm = tokenize_to_memmap(tok, tiny_corpus, out, dtype="uint16")
    assert out.exists()

    text = tiny_corpus.read_text(encoding="utf-8")
    expected = tok.encode(text)
    np.testing.assert_array_equal(np.asarray(mm), expected)

    # reload and sample
    reloaded = load_token_file(out, "uint16")
    x, y = get_batch(reloaded, 8, 32, np.random.default_rng(0))
    assert x.dtype == np.int64
    np.testing.assert_array_equal(x[:, 1:], y[:, :-1])
